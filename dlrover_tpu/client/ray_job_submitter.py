"""Submit a dlrover-tpu job to a Ray cluster.

Role parity: ``dlrover/client/platform/ray/ray_job_submitter.py:48``
(``RayJobSubimitter`` — load a conf, submit through the Ray job
submission API, poll until terminal). The submitted entrypoint boots the
master (``dlrover_tpu.master.main --platform ray``), which then scales
worker actors through the ActorScaler.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("client.ray_submitter")

TERMINAL_STATES = {"SUCCEEDED", "FAILED", "STOPPED"}


def load_conf(conf_path: str) -> Dict[str, Any]:
    with open(conf_path) as f:
        return json.load(f)


class RayJobSubmitter:
    def __init__(
        self,
        conf_path: str = "",
        conf: Optional[Dict[str, Any]] = None,
        address: str = "auto",
        client=None,  # injectable JobSubmissionClient-compatible object
    ):
        self._conf = conf if conf is not None else load_conf(conf_path)
        if client is None:
            from ray.job_submission import JobSubmissionClient  # deferred

            client = JobSubmissionClient(address)
        self._client = client

    def _entrypoint(self) -> str:
        import shlex

        job_name = self._conf.get("job_name", "ray-job")
        conf_json = json.dumps(self._conf)
        return (
            "python -m dlrover_tpu.master.main --platform ray "
            f"--job_name {shlex.quote(job_name)} "
            f"--ray_conf {shlex.quote(conf_json)}"
        )

    def submit(self) -> str:
        job_id = self._client.submit_job(
            entrypoint=self._entrypoint(),
            runtime_env=self._conf.get("runtime_env", {}),
        )
        logger.info("submitted ray job %s", job_id)
        return job_id

    def get_status(self, job_id: str) -> str:
        return str(self._client.get_job_status(job_id))

    def wait_until_finish(self, job_id: str, timeout: float = 3600,
                          poll: float = 2.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_status(job_id)
            if status in TERMINAL_STATES:
                return status
            time.sleep(poll)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")

    def stop_job(self, job_id: str) -> bool:
        return bool(self._client.stop_job(job_id))

    def describe(self, job_id: str):
        return self._client.get_job_info(job_id)

    def logs(self, job_id: str) -> str:
        return self._client.get_job_logs(job_id)
