"""Deterministic fault injection for elasticity/recovery testing.

Role parity: the reference snapshot has no dedicated chaos framework —
its tests simulate faults with mocks and canned events (SURVEY §4/§5);
later DLRover versions grew one because mocked faults miss integration
bugs (a SIGKILLed process and a raised exception exercise different
recovery paths). This module injects *real* faults into *real* runs:

- ``kill_workers``       — SIGKILL live worker subprocesses (not a polite
  exception: the process dies mid-syscall, exactly like an OOM kill or a
  preemption).
- ``FlakyChannel``       — wraps an ``rpc.client.RpcChannel`` and fails a
  seeded, deterministic fraction of calls with UNAVAILABLE, exercising
  the retry decorators instead of bypassing them.
- ``corrupt_checkpoint`` — truncates (torn-write) or bit-flips the array
  payload of a checkpoint step, exercising restore fallback to the
  newest good step + quarantine of the bad one.

Everything is seeded/counted — a chaos test that cannot reproduce its
failure is worse than no test.
"""

from __future__ import annotations

import os
import random
import signal
from typing import Iterable, List, Optional

import grpc

from dlrover_tpu.common.log import get_logger

logger = get_logger("diagnosis.chaos")


# ---------------------------------------------------------------------------
# process faults
# ---------------------------------------------------------------------------

def kill_workers(pids: Iterable[int], sig: int = signal.SIGKILL) -> List[int]:
    """SIGKILL the given pids; returns those actually signalled."""
    killed = []
    for pid in pids:
        try:
            os.kill(pid, sig)
            killed.append(pid)
            logger.info("chaos: sent signal %d to pid %d", sig, pid)
        except ProcessLookupError:
            pass
    return killed


# ---------------------------------------------------------------------------
# rpc faults
# ---------------------------------------------------------------------------

class _InjectedUnavailable(grpc.RpcError):
    """Transient failure as the retry layer sees it."""

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self):
        return "chaos: injected UNAVAILABLE"


class _FlakyCallable:
    """Decorates a raw grpc unary callable with seeded failures.

    A failure raises *before* the real call for half the hits and *after*
    it for the other half — the latter models "the master applied my
    report but I never saw the ack", the case that catches non-idempotent
    handlers.
    """

    def __init__(self, inner, rng: random.Random, drop_rate: float,
                 stats: "FlakyStats"):
        self._inner = inner
        self._rng = rng
        self._drop = drop_rate
        self._stats = stats

    def __call__(self, *args, **kwargs):
        pre = self._rng.random() < self._drop
        post = not pre and self._rng.random() < self._drop
        if pre:
            self._stats.injected += 1
            raise _InjectedUnavailable()
        out = self._inner(*args, **kwargs)
        if post:
            self._stats.injected += 1
            raise _InjectedUnavailable()
        return out


class FlakyStats:
    injected = 0


def make_flaky(channel, drop_rate: float = 0.3, seed: int = 0) -> FlakyStats:
    """Patch an ``RpcChannel`` in place so its raw grpc callables fail a
    deterministic fraction of the time. Injects BELOW the channel's
    retry layer (``RpcChannel._invoke`` wraps ``get/report``), so the
    production retry path is what absorbs the faults. Returns the
    stats counter."""
    stats = FlakyStats()
    rng = random.Random(seed)
    channel._get = _FlakyCallable(channel._get, rng, drop_rate, stats)
    channel._report = _FlakyCallable(channel._report, rng, drop_rate, stats)
    return stats


class _DyingCallable:
    """A raw grpc callable that dies PERMANENTLY after ``after_calls``
    successful invocations — the mid-transfer-holder-death model: the
    holder streams some chunks, then its process is gone and every
    later call fails with UNAVAILABLE (not a one-off blip a retry
    absorbs — the fetcher must fall over to the NEXT replica)."""

    def __init__(self, inner, after_calls: int, stats: "FlakyStats"):
        self._inner = inner
        self._remaining = int(after_calls)
        self._stats = stats

    def __call__(self, *args, **kwargs):
        if self._remaining <= 0:
            self._stats.injected += 1
            raise _InjectedUnavailable()
        self._remaining -= 1
        return self._inner(*args, **kwargs)


def kill_channel_after(channel, after_calls: int) -> FlakyStats:
    """Patch an ``RpcChannel`` so its raw callables serve exactly
    ``after_calls`` more requests EACH and then die for good (below
    the retry layer, like every injector here). Models a replica
    holder lost MID-TRANSFER — fetch-side (get) or push-side
    (report). Returns the injection counter."""
    stats = FlakyStats()
    channel._get = _DyingCallable(channel._get, after_calls, stats)
    channel._report = _DyingCallable(channel._report, after_calls, stats)
    return stats


def corrupt_replica_chunk(store, owner: int, index: int = 0,
                          seed: int = 0) -> Optional[tuple]:
    """Flip one payload byte of a COMMITTED chunk inside a live
    ReplicaStore — silent DRAM bitrot on a holder. The frame's crc32
    must catch it at fetch time (the fetcher retries, then falls to
    the next holder); returns the (leaf, seq) corrupted, or None."""
    import random as _random

    with store._lock:
        entries = store._committed.get(int(owner)) or []
        entry = entries[0] if entries else None  # newest retained step
        if not entry or not entry["chunks"]:
            return None
        keys = sorted(entry["chunks"])
        key = keys[index % len(keys)]
        frame = bytearray(entry["chunks"][key])
        # flip a byte INSIDE the payload (past the 4-byte length prefix
        # and the JSON header), so the header still parses and only the
        # crc check can notice
        import struct as _struct

        (hlen,) = _struct.unpack_from(">I", frame, 0)
        payload_start = 4 + hlen
        if payload_start >= len(frame):
            return None
        off = payload_start + _random.Random(seed).randrange(
            len(frame) - payload_start)
        frame[off] ^= 0xFF
        entry["chunks"][key] = bytes(frame)
    logger.info("chaos: flipped a payload byte of replica chunk "
                "owner=%d leaf=%d seq=%d", owner, key[0], key[1])
    return key


def freeze_replicator(replicator) -> None:
    """Pause a SnapshotReplicator's push cycles (the expired-cadence
    fault: the job keeps training while its replicas go stale)."""
    replicator.paused = True
    logger.info("chaos: snapshot replicator frozen (cadence expired)")


# ---------------------------------------------------------------------------
# storage faults
# ---------------------------------------------------------------------------

def corrupt_checkpoint(step_dir: str, mode: str = "truncate",
                       nbytes: int = 64, seed: int = 0) -> Optional[str]:
    """Damage the largest data file under a checkpoint step directory.

    ``mode="truncate"`` cuts the file to half (the torn-write model — a
    killed writer leaves a short file, and reads fail loudly).
    ``mode="flip"`` XORs ``nbytes`` random bytes (bitrot model; note
    formats without payload checksums may read flipped bytes back
    silently). Returns the corrupted path, or None if no file found.
    Metadata files are skipped — the target is the array payload."""
    candidates = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            # skip metadata: damaged metadata is merely logged by readers;
            # the torn-write target is the array payload
            if name.endswith((".json", ".txt")) or name.startswith((".", "_")):
                continue
            if "METADATA" in name.upper() or "manifest" in name.lower():
                continue
            path = os.path.join(root, name)
            candidates.append((os.path.getsize(path), path))
    if not candidates:
        return None
    if mode == "truncate":
        # a writer killed mid-flush leaves MANY short files (ocdbt spreads
        # one array over several data files) — truncate all of them
        for size, path in candidates:
            with open(path, "r+b") as f:
                f.truncate(size // 2)
        logger.info("chaos: truncated %d files under %s", len(candidates),
                    step_dir)
        return max(candidates)[1]
    _, path = max(candidates)
    size = os.path.getsize(path)
    rng = random.Random(seed)
    with open(path, "r+b") as f:
        for _ in range(min(nbytes, size)):
            off = rng.randrange(size)
            f.seek(off)
            byte = f.read(1)
            f.seek(off)
            f.write(bytes([byte[0] ^ 0xFF]))
    logger.info("chaos: flipped %d bytes of %s", nbytes, path)
    return path


# NaN injection intentionally lives in the executor tests, not here: the
# guardrail tests (tests/test_executor.py) poison a batch directly
# (x * jnp.nan), which needs no side-channel contract with the jitted
# step. A loss-wrapper injector was removed for that reason.
