"""In-job hang detection, independent of the master.

Role parity: ``atorch/atorch/fault_tolerance/hanging_detector.py:10-145``
(``HangingDetector`` — per-worker heartbeat thread; missing heartbeats ⇒
request relaunch) and ``custom_agent.py:19`` (``LocalDetectHangingAgent``).

TPU-first: the thing that hangs on TPU is a collective waiting on a dead
peer inside one XLA program — the Python thread stays alive while the
device blocks. So the heartbeat is driven from the *host* side of the step
loop (``report_normal()`` after each device-synced step), and the monitor
escalates through a callback (agent restart / master report) when the gap
exceeds the timeout.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("diagnosis.hang")

_heartbeat_path: Optional[str] = None
_heartbeat_resolved = False


def touch_heartbeat() -> None:
    """Per-step liveness beacon for the agent's hang-relaunch mode
    (reference ``LocalDetectHangingAgent`` / ``--relaunch_on_hanging``).

    When the agent exports ``NodeEnv.HEARTBEAT_DIR``, each worker touches
    ``hb_<LOCAL_RANK>`` after every host-synced step; the agent monitor
    loop treats a stale newest-beat as a hang (a collective blocked on a
    dead peer keeps the process alive but the step loop frozen) and
    restarts the workers. No-op when the env var is absent."""
    global _heartbeat_path, _heartbeat_resolved
    if not _heartbeat_resolved:
        _heartbeat_resolved = True
        from dlrover_tpu.common.constants import NodeEnv

        directory = os.environ.get(NodeEnv.HEARTBEAT_DIR, "")
        if directory:
            os.makedirs(directory, exist_ok=True)
            _heartbeat_path = os.path.join(
                directory, f"hb_{os.environ.get('LOCAL_RANK', '0')}"
            )
    if _heartbeat_path is None:
        return
    with open(_heartbeat_path, "w") as f:
        f.write(str(time.time()))
    # a beat means any declared long phase is over: drop the lease so a
    # REAL hang right after a fast restore/recompile is judged promptly
    # instead of hiding behind the remainder of the lease window
    try:
        os.remove(_lease_path(_heartbeat_path))
    except OSError:
        pass


def _lease_path(heartbeat_path: str) -> str:
    # swap the basename prefix only — the heartbeat DIRECTORY itself
    # contains "hb_" (tempfile prefix "dlrover_hb_"), so a whole-path
    # replace would point into a nonexistent directory
    d, name = os.path.split(heartbeat_path)
    return os.path.join(d, name.replace("hb_", "lease_", 1))


def announce_long_phase(seconds: float) -> None:
    """Declare a bounded no-heartbeat window (world-change recompile,
    rollback restore): writes a lease deadline next to the heartbeat
    file. The agent treats an unexpired lease as liveness, so a known
    minutes-long in-process phase isn't misread as a hang — while a
    REAL hang during the phase still trips once the lease expires. The
    next heartbeat (first step after the phase) clears the lease.
    No-op when hang-relaunch is off."""
    global _heartbeat_path
    if _heartbeat_path is None:
        touch_heartbeat()  # resolves the path on first use
    if _heartbeat_path is None:
        return
    with open(_lease_path(_heartbeat_path), "w") as f:
        f.write(str(time.time() + seconds))


class HangingDetector:
    def __init__(
        self,
        timeout_secs: float = 1800.0,
        check_interval_secs: float = 30.0,
        on_hang: Optional[Callable[[float], None]] = None,
        monitor: bool = True,
    ):
        self._timeout = timeout_secs
        self._interval = check_interval_secs
        self._on_hang = on_hang
        self._monitor_enabled = monitor
        self._last_normal = time.time()
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.hang_detected = False

    def start(self):
        if not self._monitor_enabled or self._thread is not None:
            return
        with self._lock:
            self._last_normal = time.time()
        self._thread = threading.Thread(
            target=self._watch, name="hang-detector", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def report_normal(self):
        """Call after each completed (host-synced) training step."""
        with self._lock:
            self._last_normal = time.time()
            self.hang_detected = False

    def seconds_since_progress(self) -> float:
        with self._lock:
            return time.time() - self._last_normal

    def _watch(self):
        while not self._stopped.wait(self._interval):
            # check-and-set atomically with the gap read: a report_normal
            # racing between the read and the set would otherwise leave a
            # stale hang_detected=True (and a spurious on_hang) for a job
            # that just made progress
            with self._lock:
                gap = time.time() - self._last_normal
                fire = gap > self._timeout and not self.hang_detected
                if fire:
                    self.hang_detected = True
            if fire:
                logger.warning("no training progress for %.0fs", gap)
                if self._on_hang is not None:
                    try:
                        self._on_hang(gap)
                    except Exception:  # noqa: BLE001
                        logger.exception("on_hang callback failed")
