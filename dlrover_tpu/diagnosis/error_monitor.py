"""Failure classification on the master.

Role parity: ``dlrover/python/master/monitor/error_monitor.py``
(``ErrorLogMonitor``) — turns raw failure reports from agents into a
classified, deduplicated record the job manager and operators act on.

TPU-first classification: XLA/TPU-specific signatures (device halt, ICI
link error, HBM OOM) are recognized alongside generic Python tracebacks,
because they imply different actions (hardware cordon vs relaunch vs
memory bump).
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.constants import (
    NodeExitReason,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger

logger = get_logger("diagnosis.errors")

# Signature -> (classified exit reason). Order matters: first match wins.
_ERROR_SIGNATURES = [
    (re.compile(r"RESOURCE_EXHAUSTED|out of memory|HBM OOM", re.I),
     NodeExitReason.OOM),
    (re.compile(r"ICI|interconnect|link.*(down|error)|DEADLINE_EXCEEDED.*"
                r"collective", re.I),
     NodeExitReason.HARDWARE_ERROR),
    (re.compile(r"halted|device.*(unavailable|failure)|INTERNAL.*TPU", re.I),
     NodeExitReason.HARDWARE_ERROR),
    (re.compile(r"preempt", re.I), NodeExitReason.PREEMPTED),
    (re.compile(r"SyntaxError|ImportError|ModuleNotFoundError|NameError"),
     NodeExitReason.FATAL_ERROR),
]


@dataclass
class ErrorRecord:
    timestamp: float
    node_id: int
    level: str
    reason: str
    message: str


@dataclass
class ErrorLogMonitor:
    max_records: int = 200
    records: List[ErrorRecord] = field(default_factory=list)

    def __post_init__(self):
        self._lock = threading.Lock()

    def process_error(
        self, node_id: int, restart_count: int, error_data: str, level: str
    ) -> str:
        """Classify and record; returns the inferred NodeExitReason."""
        reason = classify_error(error_data)
        record = ErrorRecord(
            timestamp=time.time(),
            node_id=node_id,
            level=level,
            reason=reason,
            message=error_data[:2048],
        )
        with self._lock:
            self.records.append(record)
            if len(self.records) > self.max_records:
                del self.records[: -self.max_records]
        log = (
            logger.error
            if level in (TrainingExceptionLevel.NODE_ERROR,
                         TrainingExceptionLevel.PROCESS_ERROR)
            else logger.warning
        )
        log(
            "node %d failure (level=%s restarts=%d reason=%s): %s",
            node_id, level, restart_count, reason, error_data[:512],
        )
        return reason

    def node_error_counts(self) -> Dict[int, int]:
        with self._lock:
            counts: Dict[int, int] = {}
            for r in self.records:
                counts[r.node_id] = counts.get(r.node_id, 0) + 1
            return counts

    def failed_node_ids(
        self,
        since_timestamp: float = 0.0,
        levels: tuple = (
            TrainingExceptionLevel.PROCESS_ERROR,
            TrainingExceptionLevel.NODE_ERROR,
        ),
    ) -> List[int]:
        """Node ids with hard failures since ``since_timestamp`` — the
        query surface consumers (e.g. the acceleration engine's dead-rank
        watcher) poll instead of waiting out task timeouts."""
        with self._lock:
            return sorted({
                r.node_id for r in self.records
                if r.timestamp >= since_timestamp and r.level in levels
            })


def classify_error(error_data: str) -> str:
    for pattern, reason in _ERROR_SIGNATURES:
        if pattern.search(error_data or ""):
            return reason
    return NodeExitReason.UNKNOWN_ERROR
