"""Failure classification on the master.

Role parity: ``dlrover/python/master/monitor/error_monitor.py``
(``ErrorLogMonitor``) — turns raw failure reports from agents into a
classified, deduplicated record the job manager and operators act on.

TPU-first classification: XLA/TPU-specific signatures (device halt, ICI
link error, HBM OOM) are recognized alongside generic Python tracebacks,
because they imply different actions (hardware cordon vs relaunch vs
memory bump).
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.constants import (
    NodeExitReason,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)

logger = get_logger("diagnosis.errors")

# Signature -> (classified exit reason). Order matters: first match wins.
_ERROR_SIGNATURES = [
    (re.compile(r"RESOURCE_EXHAUSTED|out of memory|HBM OOM", re.I),
     NodeExitReason.OOM),
    (re.compile(r"ICI|interconnect|link.*(down|error)|DEADLINE_EXCEEDED.*"
                r"collective", re.I),
     NodeExitReason.HARDWARE_ERROR),
    (re.compile(r"halted|device.*(unavailable|failure)|INTERNAL.*TPU", re.I),
     NodeExitReason.HARDWARE_ERROR),
    (re.compile(r"preempt", re.I), NodeExitReason.PREEMPTED),
    (re.compile(r"SyntaxError|ImportError|ModuleNotFoundError|NameError"),
     NodeExitReason.FATAL_ERROR),
]


@dataclass
class ErrorRecord:
    timestamp: float
    node_id: int
    level: str
    reason: str
    message: str


@dataclass
class ErrorLogMonitor:
    max_records: int = 200
    records: List[ErrorRecord] = field(default_factory=list)
    # repeated IDENTICAL errors (same node + classified code) inside
    # this window are counted, not logged: a crash-looping rank at a
    # 2s monitor cadence otherwise floods the master log at ~30
    # lines/min/rank and buries the first, informative, occurrence
    dedup_window_secs: float = 60.0

    def __post_init__(self):
        self._lock = threading.Lock()
        # (node_id, reason) -> [window_start_ts, suppressed_count]
        self._recent: Dict[tuple, list] = {}
        reg = get_registry()
        self._c_errors = reg.counter(
            tm.ERROR_REPORTS, help="failure reports classified")
        self._c_deduped = reg.counter(
            tm.ERRORS_DEDUPED,
            help="repeated identical errors suppressed from the log "
                 "inside the dedup window")

    def process_error(
        self, node_id: int, restart_count: int, error_data: str, level: str
    ) -> str:
        """Classify and record; returns the inferred NodeExitReason."""
        reason = classify_error(error_data)
        now = time.time()
        record = ErrorRecord(
            timestamp=now,
            node_id=node_id,
            level=level,
            reason=reason,
            message=error_data[:2048],
        )
        self._c_errors.inc()
        key = (node_id, reason)
        with self._lock:
            self.records.append(record)
            if len(self.records) > self.max_records:
                del self.records[: -self.max_records]
            window = self._recent.get(key)
            if window is not None and (
                now - window[0] < self.dedup_window_secs
            ):
                window[1] += 1
                suppressed = window[1]
            else:
                prior = window[1] if window is not None else 0
                self._recent[key] = [now, 0]
                suppressed = 0
        if suppressed:
            # duplicate inside the window: count it, keep the log quiet
            self._c_deduped.inc()
            logger.debug(
                "node %d repeat failure (reason=%s, %d suppressed in "
                "window)", node_id, reason, suppressed,
            )
            return reason
        # first occurrence (or window expired): log + event-timeline
        # record; the log line carries the event seq so operators can
        # jump from the log to the structured record
        event = emit_event(
            EventKind.ERROR_REPORT, error_code=reason,
            failed_node=node_id, level=level,
            restart_count=restart_count,
            message=error_data[:512],
            repeats_last_window=prior,
        )
        log = (
            logger.error
            if level in (TrainingExceptionLevel.NODE_ERROR,
                         TrainingExceptionLevel.PROCESS_ERROR)
            else logger.warning
        )
        log(
            "node %d failure (level=%s restarts=%d reason=%s)"
            "%s [event #%s]: %s",
            node_id, level, restart_count, reason,
            (f" (+{prior} identical suppressed in the last "
             f"{self.dedup_window_secs:.0f}s)" if prior else ""),
            event.get("seq", "-"), error_data[:512],
        )
        return reason

    def node_error_counts(self) -> Dict[int, int]:
        with self._lock:
            counts: Dict[int, int] = {}
            for r in self.records:
                counts[r.node_id] = counts.get(r.node_id, 0) + 1
            return counts

    def failed_node_ids(
        self,
        since_timestamp: float = 0.0,
        levels: tuple = (
            TrainingExceptionLevel.PROCESS_ERROR,
            TrainingExceptionLevel.NODE_ERROR,
        ),
    ) -> List[int]:
        """Node ids with hard failures since ``since_timestamp`` — the
        query surface consumers (e.g. the acceleration engine's dead-rank
        watcher) poll instead of waiting out task timeouts."""
        with self._lock:
            return sorted({
                r.node_id for r in self.records
                if r.timestamp >= since_timestamp and r.level in levels
            })


def classify_error(error_data: str) -> str:
    for pattern, reason in _ERROR_SIGNATURES:
        if pattern.search(error_data or ""):
            return reason
    return NodeExitReason.UNKNOWN_ERROR
