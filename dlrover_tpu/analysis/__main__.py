"""``python -m dlrover_tpu.analysis`` entry point."""

import sys

from dlrover_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
