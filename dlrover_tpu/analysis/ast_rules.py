"""Framework AST linter: distributed-correctness pitfalls as rules.

Each rule has a stable id (``DLR0xx``), a one-line message, and a fix-it
hint. The rules encode the control-plane discipline the ElasWave /
fault-tolerant-HSDP line of work (PAPERS.md) identifies as the dominant
source of silent hangs and mystery slowdowns at scale:

  DLR001 grpc-no-timeout       an RPC invocation that can block forever
  DLR002 swallowed-exception   ``except Exception`` that hides the error
  DLR003 non-daemon-thread     a background thread that pins shutdown
  DLR004 impure-in-jit         host time/randomness captured at trace time
  DLR005 shared-mutable-default mutable defaults aliased across instances
  DLR006 host-sync-on-metrics  float()/.item()/np.asarray() on step
                               metrics — a device sync on the hot loop
  DLR007 unregistered-metric-name  a string literal handed to a
                               telemetry API instead of a
                               telemetry.names constant
  DLR008 failure-event-no-code a failure-class event emitted without a
                               machine-readable error_code

Rules are deliberately syntactic (no type inference): they over-approximate
in ways the checked-in baseline absorbs, and under-approximate in ways unit
fixtures pin (``tests/test_analysis.py`` has one firing and one clean case
per rule id).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from dlrover_tpu.analysis.findings import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)

LOG_METHODS_OK = {"exception", "error", "warning", "critical", "info",
                  "debug", "log", "print_exc"}
GRPC_FACTORY_METHODS = {"unary_unary", "unary_stream", "stream_unary",
                        "stream_stream"}
IMPURE_CALLS = {
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("datetime", "now"), ("datetime", "utcnow"),
    ("np", "random"), ("numpy", "random"),
    ("random", "random"), ("random", "randint"), ("random", "uniform"),
    ("random", "choice"), ("random", "shuffle"), ("random", "sample"),
    ("os", "urandom"),
}
MUTABLE_CALLS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                 "Counter", "deque"}
# DLR006: host-materialization calls that block on the device when
# applied to step metrics (each forces jax's async dispatch queue to
# drain up to that value — the exact stall the executor's lagged
# metrics window exists to avoid)
SYNC_CALLS = {"float", "int", "bool"}
SYNC_ARRAY_CALLS = {"asarray", "array", "device_get"}
# DLR007: telemetry APIs whose FIRST argument is a metric/event name.
# Lowercase method names only — collections.Counter(...) etc. don't
# collide. The telemetry package itself (names.py + registry internals)
# is exempt: it is where names are allowed to be literal.
TELEMETRY_NAME_CALLS = {"counter", "gauge", "histogram", "emit_event"}
TELEMETRY_PKG_FRAGMENT = "telemetry/"
# DLR008: EventKind constants that mark a FAILURE edge. A failure
# record without a stable error_code cannot be classified by the MTTR /
# goodput derivations or rate-limited by the error monitor — operators
# get an incident with no machine-readable cause. The attribute names
# below (and their string values, for sites that inline the literal)
# must carry a non-empty error_code at every emit site.
FAILURE_EVENT_ATTRS = {
    "NONFINITE_STEP", "WORKER_FAILED", "HANG_DETECTED",
    "PREEMPT_NOTICE", "RDZV_TIMEOUT", "CKPT_MIRROR_TIMEOUT",
    "ERROR_REPORT", "DIAG_STRAGGLER", "DIAG_NODE_HANG",
    "DATA_SHARD_TIMEOUT", "SERVE_REQUEST_EVICTED",
    "SERVE_LEASE_EXPIRED", "SERVE_SLO_VIOLATION",
    "REPLICA_PUSH_FAILED", "REPLICA_PLAN_DEGRADED",
    "REPLICA_HOLDER_LOST", "PEER_REBUILD_FALLBACK",
    "DIAG_DURABILITY", "READINESS_DEGRADED",
}
FAILURE_EVENT_VALUES = {
    "nonfinite_step", "worker_failed", "hang_detected",
    "preempt_notice", "rdzv_timeout", "ckpt_mirror_timeout",
    "error_report", "diag_straggler", "diag_node_hang",
    "data_shard_timeout", "serve_request_evicted",
    "serve_lease_expired", "serve_slo_violation",
    "replica_push_failed", "replica_plan_degraded",
    "replica_holder_lost", "peer_rebuild_fallback",
    "diag_durability", "readiness_degraded",
}


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords) or any(
        kw.arg is None for kw in call.keywords  # **kwargs may carry it
    )


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        name = _dotted(node.func).rsplit(".", 1)[-1]
        return name in MUTABLE_CALLS and not node.args and not node.keywords
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module,
                 enabled: Optional[Set[str]] = None):
        self.path = path
        self.tree = tree
        self.enabled = enabled
        self.findings: List[Finding] = []
        self._scopes: List[str] = []
        self._jit_depth = 0
        self._imports_grpc = any(
            isinstance(n, (ast.Import, ast.ImportFrom))
            and "grpc" in ast.dump(n)
            for n in tree.body
        )
        # names bound (anywhere in the module) from channel.unary_unary(..)
        # factories: later bare calls through them must carry timeout=
        self._grpc_callables: Set[str] = set()
        if self._imports_grpc:
            for node in ast.walk(tree):
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                if (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Attribute)
                        and value.func.attr in GRPC_FACTORY_METHODS):
                    for tgt in node.targets:
                        name = _dotted(tgt)
                        if name:
                            self._grpc_callables.add(name)

    # -- plumbing -----------------------------------------------------------

    def _emit(self, rule_id: str, node: ast.AST, message: str,
              fixit: str = ""):
        if self.enabled is not None and rule_id not in self.enabled:
            return
        self.findings.append(Finding(
            rule_id=rule_id, path=self.path,
            line=getattr(node, "lineno", 0), message=message, fixit=fixit,
            scope=".".join(self._scopes),
        ))

    def _visit_scope(self, node, name: str):
        self._scopes.append(name)
        jit = isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and self._is_jitted(node)
        if jit:
            self._jit_depth += 1
        self.generic_visit(node)
        if jit:
            self._jit_depth -= 1
        self._scopes.pop()

    @staticmethod
    def _is_jitted(node) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = _dotted(target)
            if name.endswith("jit") or name in ("pjit", "jax.pjit"):
                return True
            # functools.partial(jax.jit, ...)
            if (isinstance(dec, ast.Call) and name.endswith("partial")
                    and dec.args
                    and _dotted(dec.args[0]).endswith("jit")):
                return True
        return False

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scopes.append(node.name)
        self._check_class_mutable_defaults(node)
        self._scopes.pop()
        self._visit_scope(node, node.name)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._check_mutable_defaults(node)
        self._visit_scope(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._check_mutable_defaults(node)
        self._visit_scope(node, node.name)

    # -- DLR001: grpc calls without a deadline ------------------------------

    def visit_Call(self, node: ast.Call):
        if self._imports_grpc:
            self._check_grpc_timeout(node)
        if self._jit_depth > 0:
            self._check_impure_in_jit(node)
        self._check_host_sync_on_metrics(node)
        self._check_telemetry_name_literal(node)
        self._check_failure_event_code(node)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "Thread"):
            self._check_thread_daemon(node)
        elif (isinstance(node.func, ast.Name)
              and node.func.id == "Thread"):
            self._check_thread_daemon(node)
        self.generic_visit(node)

    def _check_grpc_timeout(self, node: ast.Call):
        name = _dotted(node.func)
        is_stub_call = name in self._grpc_callables
        # .future(...) on a multicallable (async fan-out idiom): the
        # deadline must ride the .future() call — .result() alone cannot
        # cancel the in-flight RPC
        is_future_call = (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "future"
                          and not name.startswith(("concurrent.",
                                                   "asyncio.")))
        if (is_stub_call or is_future_call) and not _has_kwarg(
                node, "timeout"):
            self._emit(
                "DLR001", node,
                f"gRPC invocation `{name or node.func.attr}(...)` without "
                f"a timeout= deadline: a dead peer blocks this call (and "
                f"the failover logic behind it) forever",
                "pass timeout=<seconds>; route it from the caller's "
                "config rather than hardcoding",
            )

    # -- DLR002: except Exception that swallows -----------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException")
        ) or (
            isinstance(node.type, ast.Attribute)
            and node.type.attr in ("Exception", "BaseException")
        )
        if broad and not self._handler_surfaces_error(node):
            self._emit(
                "DLR002", node,
                "broad `except Exception` swallows the error silently: on "
                "a failover/rendezvous path this converts a crash into a "
                "hang or a wrong decision with no trace",
                "narrow the exception type, or log the error "
                "(logger.warning/.exception) before continuing, or "
                "re-raise",
            )
        self.generic_visit(node)

    @staticmethod
    def _handler_surfaces_error(node: ast.ExceptHandler) -> bool:
        for sub in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                attr = (sub.func.attr
                        if isinstance(sub.func, ast.Attribute) else
                        sub.func.id if isinstance(sub.func, ast.Name)
                        else "")
                if attr in LOG_METHODS_OK:
                    return True
        return False

    # -- DLR003: background threads that outlive shutdown -------------------

    def _check_thread_daemon(self, node: ast.Call):
        name = _dotted(node.func)
        if name and not (name == "Thread"
                         or name.endswith(".Thread")):
            return
        if not _has_kwarg(node, "daemon"):
            self._emit(
                "DLR003", node,
                "Thread(...) without daemon=: a non-daemon background "
                "thread blocks interpreter exit, turning a master/agent "
                "crash-restart into a hang",
                "pass daemon=True (or daemon=False with an explicit "
                "join on the shutdown path)",
            )

    # -- DLR004: host impurity inside jit -----------------------------------

    def _check_impure_in_jit(self, node: ast.Call):
        name = _dotted(node.func)
        parts = tuple(name.split("."))
        hit = tuple(parts[-2:]) in IMPURE_CALLS or name.startswith(
            ("np.random.", "numpy.random.")
        )
        if hit:
            self._emit(
                "DLR004", node,
                f"`{name}()` inside a jit-compiled function is evaluated "
                f"once at trace time and frozen into the graph — every "
                f"step reuses the same 'current' time / random draw",
                "thread host values in as arguments, or use jax.random "
                "with an explicit key",
            )

    # -- DLR006: host sync on step metrics ----------------------------------

    @staticmethod
    def _mentions_metrics(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                sub.id == "metrics" or sub.id.endswith("_metrics")
            ):
                return True
            if isinstance(sub, ast.Attribute) and (
                sub.attr == "metrics" or sub.attr.endswith("_metrics")
            ):
                return True
        return False

    def _check_host_sync_on_metrics(self, node: ast.Call):
        """float()/.item()/np.asarray()/jax.device_get() applied to a
        step-metric value: each one blocks the host on the device queue,
        so in the hot loop it caps in-flight dispatch at one step. The
        rule is name-based (values reached through ``metrics`` /
        ``*_metrics``) — deliberately over-approximate; the lagged
        materialization sites the async executor keeps ON PURPOSE live
        in the baseline ratchet."""
        name = _dotted(node.func)
        short = name.rsplit(".", 1)[-1]
        target: Optional[ast.AST] = None
        if short in SYNC_CALLS and "." not in name and node.args:
            target = node.args[0]
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            # .item() hangs off arbitrary expressions (subscripts,
            # calls) that _dotted cannot name — match the attr itself
            target = node.func.value
            short = "item"
        elif short in SYNC_ARRAY_CALLS and "." in name and node.args:
            target = node.args[0]
        if target is None or not self._mentions_metrics(target):
            return
        self._emit(
            "DLR006", node,
            f"`{name or short}(...)` on a step-metric value forces a "
            f"host-device sync: the dispatch queue drains to one step "
            f"in flight, putting Python/RPC overhead on the critical "
            f"path",
            "consume metrics through the executor's lagged window "
            "(train_window) or move the read off the per-step path",
        )

    # -- DLR007: ad-hoc metric/event names ----------------------------------

    def _check_telemetry_name_literal(self, node: ast.Call):
        """A string literal as the name argument of ``counter()`` /
        ``gauge()`` / ``histogram()`` / ``emit_event()``: names minted
        at the call site drift apart ("step_time" vs "step_time_s"
        claiming to be the same series), never reach the documented
        name table, and can silently collide with another subsystem's
        metric. All names must come from ``dlrover_tpu.telemetry.names``
        (which the rule exempts, along with the registry internals)."""
        if TELEMETRY_PKG_FRAGMENT in self.path:
            return
        name = _dotted(node.func)
        short = name.rsplit(".", 1)[-1] if name else (
            node.func.attr if isinstance(node.func, ast.Attribute) else ""
        )
        if short not in TELEMETRY_NAME_CALLS:
            return
        target = None
        if node.args:
            target = node.args[0]
        else:
            for kw in node.keywords:
                if kw.arg in ("name", "kind"):
                    target = kw.value
                    break
        if isinstance(target, ast.Constant) and isinstance(
                target.value, str):
            self._emit(
                "DLR007", node,
                f"`{short}({target.value!r})` mints a metric/event name "
                f"at the call site: unregistered names drift, collide, "
                f"and never reach the docs/observability.md name table",
                "add a constant to dlrover_tpu/telemetry/names.py and "
                "pass it instead of the literal",
            )

    # -- DLR008: failure-class events without an error code -----------------

    def _check_failure_event_code(self, node: ast.Call):
        """``emit_event(EventKind.<failure-kind>, ...)`` must carry a
        non-empty ``error_code``: failure edges without a stable machine
        code cannot be classified by the derived MTTR/goodput reports or
        deduped by the error monitor. A dynamic expression passes (the
        code is computed); only a MISSING kwarg or a constant empty
        string fires. Unlike DLR007, the telemetry package is NOT
        exempt — its own emits must carry codes too."""
        name = _dotted(node.func)
        short = name.rsplit(".", 1)[-1] if name else (
            node.func.attr if isinstance(node.func, ast.Attribute) else ""
        )
        if short != "emit_event":
            return
        kind_arg: Optional[ast.AST] = None
        if node.args:
            kind_arg = node.args[0]
        else:
            for kw in node.keywords:
                if kw.arg == "kind":
                    kind_arg = kw.value
                    break
        is_failure = False
        kind_label = ""
        if isinstance(kind_arg, ast.Attribute):
            is_failure = kind_arg.attr in FAILURE_EVENT_ATTRS
            kind_label = kind_arg.attr
        elif isinstance(kind_arg, ast.Constant) and isinstance(
                kind_arg.value, str):
            is_failure = kind_arg.value in FAILURE_EVENT_VALUES
            kind_label = kind_arg.value
        if not is_failure:
            return
        code_kw = next(
            (kw for kw in node.keywords if kw.arg == "error_code"), None
        )
        if code_kw is None and any(
                kw.arg is None for kw in node.keywords):
            return  # **kwargs may carry it — over-approximation cut
        empty_literal = (
            code_kw is not None
            and isinstance(code_kw.value, ast.Constant)
            and code_kw.value.value in ("", None)
        )
        if code_kw is None or empty_literal:
            self._emit(
                "DLR008", node,
                f"failure-class event `{kind_label}` emitted without a "
                f"non-empty error_code: the incident cannot be "
                f"classified by the MTTR/goodput derivations or deduped "
                f"by the error monitor",
                "pass error_code=<stable machine code> (e.g. \"HANG\", "
                "\"EXIT_<n>\", \"NONFINITE\") on the failure edge",
            )

    # -- DLR005: shared mutable defaults ------------------------------------

    def _check_mutable_defaults(self, node):
        args = node.args
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            if _is_mutable_literal(default):
                self._emit(
                    "DLR005", default,
                    f"mutable default argument in `{node.name}(...)`: the "
                    f"object is created once and shared by every call",
                    "default to None and construct inside the body (or "
                    "use dataclasses.field(default_factory=...))",
                )

    def _check_class_mutable_defaults(self, node: ast.ClassDef):
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign) or stmt.value is None:
                continue
            ann = ast.dump(stmt.annotation)
            if "ClassVar" in ann:
                continue
            if _is_mutable_literal(stmt.value):
                target = (stmt.target.id
                          if isinstance(stmt.target, ast.Name) else "?")
                self._emit(
                    "DLR005", stmt,
                    f"class attribute `{node.name}.{target}` holds a "
                    f"mutable default shared by every instance (and, in a "
                    f"dataclass, silently aliased across configs)",
                    "annotate as ClassVar[...] if sharing is intended, "
                    "else use field(default_factory=...)",
                )


ALL_AST_RULES = ("DLR001", "DLR002", "DLR003", "DLR004", "DLR005",
                 "DLR006", "DLR007", "DLR008", "DLR009", "DLR010",
                 "DLR011", "DLR012")

RULE_DOCS: Dict[str, str] = {
    "DLR001": "gRPC invocation without a timeout= deadline",
    "DLR002": "broad `except Exception` that swallows the error silently",
    "DLR003": "threading.Thread(...) without an explicit daemon= choice",
    "DLR004": "host time/randomness called inside a jit-compiled function",
    "DLR005": "mutable default shared across calls/instances",
    "DLR006": "host-device sync (float/int/bool, .item(), np.asarray/"
              "np.array, jax.device_get) on step-metric values in the "
              "hot loop",
    "DLR007": "string-literal metric/event name at a telemetry call "
              "site (must be a dlrover_tpu.telemetry.names constant)",
    "DLR008": "failure-class event emitted without a non-empty "
              "error_code (unclassifiable by the MTTR/goodput "
              "derivations)",
    "DLR009": "blocking call (RPC, sleep, un-timed join/queue op, "
              "device sync, listener iteration) inside a held-lock "
              "region",
    "DLR010": "instance attribute written under a lock in one method "
              "but accessed lock-free in another (mixed guard "
              "discipline)",
    "DLR011": "lock-order inversion: the package lock-acquisition "
              "graph contains a cycle (or a non-reentrant Lock is "
              "re-acquired while held)",
    "DLR012": "`# dlrlint: disable=` without a reason — suppressions "
              "must justify themselves",
}


def lint_source(
    source: str, path: str, rules: Optional[Set[str]] = None,
    counters: Optional[Dict[str, int]] = None,
) -> List[Finding]:
    """Run every (or the selected) AST rule over one file's source.
    ``counters`` (optional) accrues per-rule inline-suppression counts
    for the CLI summary."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(
            rule_id="DLR000", path=path, line=e.lineno or 0,
            message=f"syntax error: {e.msg}",
        )]
    linter = _Linter(path, tree, enabled=rules)
    linter.visit(tree)
    findings = apply_suppressions(
        linter.findings, scan_suppressions(source), counters=counters)
    if rules is not None:
        findings = [f for f in findings
                    if f.rule_id in rules or f.rule_id == "DLR012"]
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def lint_paths(
    paths: List[str], root: str, rules: Optional[Set[str]] = None,
    counters: Optional[Dict[str, int]] = None,
) -> List[Finding]:
    """Lint every ``.py`` file under ``paths``; finding paths are
    reported relative to ``root`` so baseline keys are checkout-stable."""
    findings: List[Finding] = []
    for path in paths:
        files: List[str] = []
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git")
                )
                files.extend(
                    os.path.join(dirpath, f) for f in sorted(filenames)
                    if f.endswith(".py")
                )
        elif path.endswith(".py"):
            files.append(path)
        for fname in files:
            with open(fname, encoding="utf-8") as fh:
                src = fh.read()
            rel = os.path.relpath(os.path.abspath(fname),
                                  os.path.abspath(root))
            findings.extend(
                lint_source(src, rel.replace(os.sep, "/"), rules=rules,
                            counters=counters)
            )
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings
