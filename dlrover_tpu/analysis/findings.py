"""Findings + baseline: the shared currency of both static passes.

A ``Finding`` is one rule violation at one site. The checked-in
``baseline.json`` is the allowlist of findings that existed when a rule
was introduced: the linter exits non-zero only on findings *outside* the
baseline, so the repo is lint-clean at HEAD and every new violation fails
loudly while legacy sites are paid down incrementally (the
ratchet-baseline pattern of ruff/ESLint ``--add-noqa`` workflows, but as
one reviewable JSON file).

Baseline entries are keyed by ``rule_id::path::scope`` (scope = the
enclosing ``Class.method`` qualname) with a *count*, not a line number —
unrelated edits that shift lines don't churn the baseline, while adding a
second violation inside an already-baselined scope still fails.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

BASELINE_VERSION = 1

# ``# dlrlint: disable=DLR009 <reason>`` — the reason is mandatory; a
# bare disable still suppresses (so the site does not double-report)
# but is itself a DLR012 finding, keeping suppressions reviewable.
_SUPPRESS = re.compile(
    r"#\s*dlrlint:\s*disable=([A-Z0-9,\s]+?)(?:\s+([^\s].*))?$")


def scan_suppressions(source: str) -> Dict[int, Tuple[Set[str], str]]:
    """Per-line inline-suppression table: line -> (rule ids, reason)."""
    table: Dict[int, Tuple[Set[str], str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        table[lineno] = (rules, (m.group(2) or "").strip())
    return table


def apply_suppressions(
    findings: List["Finding"],
    table: Dict[int, Tuple[Set[str], str]],
    counters: Optional[Dict[str, int]] = None,
) -> List["Finding"]:
    """Drop findings whose anchor line carries a matching disable
    comment; emit a DLR012 finding for every bare (reason-less)
    disable that actually suppressed something. ``counters`` (if
    given) accrues suppressed counts per rule id for the CLI summary.
    """
    kept: List[Finding] = []
    bare_hits: Dict[int, Finding] = {}
    for f in findings:
        entry = table.get(f.line)
        if entry and f.rule_id in entry[0]:
            if counters is not None:
                counters[f.rule_id] = counters.get(f.rule_id, 0) + 1
            if not entry[1] and f.line not in bare_hits:
                bare_hits[f.line] = Finding(
                    rule_id="DLR012", path=f.path, line=f.line,
                    message=f"dlrlint disable of {f.rule_id} without "
                            f"a reason: suppressions must say why or "
                            f"they rot invisibly",
                    fixit="append the justification: "
                          "`# dlrlint: disable="
                          f"{f.rule_id} <why this site is safe>`",
                    scope=f.scope)
            continue
        kept.append(f)
    kept.extend(bare_hits.values())
    return kept


@dataclass(frozen=True)
class Finding:
    rule_id: str
    path: str  # repo-relative (or fixture-relative) posix path
    line: int
    message: str
    fixit: str = ""
    scope: str = ""  # enclosing Class.method qualname ("" = module level)

    @property
    def baseline_key(self) -> str:
        return f"{self.rule_id}::{self.path}::{self.scope}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        scope = f" [{self.scope}]" if self.scope else ""
        out = f"{self.rule_id} {loc}{scope}: {self.message}"
        if self.fixit:
            out += f"\n    fix: {self.fixit}"
        return out


@dataclass
class Baseline:
    """Allowlist of pre-existing findings, keyed scope-wise with counts."""

    entries: Dict[str, int] = field(default_factory=dict)
    # per-entry rationale (key -> why this legacy site is tolerated);
    # purely documentary — the ratchet ignores it, load/save round-trip
    # it, and --write-baseline preserves notes for surviving keys
    notes: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as fh:
            data = json.load(fh)
        if data.get("version") != BASELINE_VERSION:
            raise ValueError(
                f"baseline {path} has version {data.get('version')!r}; "
                f"this linter writes version {BASELINE_VERSION} "
                f"(regenerate with --write-baseline)"
            )
        return cls(entries=dict(data.get("entries", {})),
                   notes=dict(data.get("notes", {})))

    def save(self, path: str):
        payload = {
            "version": BASELINE_VERSION,
            "entries": {k: self.entries[k] for k in sorted(self.entries)},
        }
        notes = {k: self.notes[k] for k in sorted(self.notes)
                 if k in self.entries}
        if notes:
            payload["notes"] = notes
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=False)
            fh.write("\n")

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        entries: Dict[str, int] = {}
        for f in findings:
            entries[f.baseline_key] = entries.get(f.baseline_key, 0) + 1
        return cls(entries=entries)

    def filter(
        self, findings: Iterable[Finding]
    ) -> Tuple[List[Finding], List[str]]:
        """(new findings not covered by the baseline, stale keys).

        Stale keys — baseline entries with no remaining finding — are
        reported so the allowlist ratchets DOWN as sites get fixed
        (a stale entry would otherwise mask a future regression at the
        same scope).
        """
        budget = dict(self.entries)
        new: List[Finding] = []
        for f in findings:
            if budget.get(f.baseline_key, 0) > 0:
                budget[f.baseline_key] -= 1
            else:
                new.append(f)
        stale = sorted(k for k, v in budget.items() if v > 0)
        return new, stale
