"""``python -m dlrover_tpu.analysis`` / ``tpurun lint`` / ``tpulint``.

Runs both static passes and exits non-zero on any finding outside the
checked-in baseline:

  AST pass    rule-based lint over the framework sources (DLR0xx)
  graph pass  SPMD lint of the compiled train step (G10x), including the
              planner-vs-HLO collective byte audit over all four MoE
              dispatches

The graph pass needs no accelerator: it compiles tiny models against the
host CPU backend (8 virtual devices) exactly like tier-1 CI, so operators
can run the full gate pre-submit in under a minute.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional


def _changed_files(root: str, ref: str) -> Optional[List[str]]:
    """Existing ``.py`` files changed vs ``ref`` (committed or not).
    ``ref`` = "<merge-base>" resolves the merge-base with main. Returns
    None when git cannot answer (not a checkout, unknown ref)."""
    def _git(*argv: str) -> Optional[str]:
        try:
            out = subprocess.run(
                ("git", "-C", root) + argv, capture_output=True,
                text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return out.stdout if out.returncode == 0 else None

    if ref == "<merge-base>":
        base = None
        for main in ("origin/main", "main", "origin/master", "master"):
            base = _git("merge-base", "HEAD", main)
            if base is not None:
                break
        if base is None:
            return None
        ref = base.strip()
    diff = _git("diff", "--name-only", ref)
    if diff is None:
        return None
    files = []
    for rel in diff.splitlines():
        if not rel.endswith(".py"):
            continue
        full = os.path.join(root, rel)
        if os.path.exists(full):
            files.append(full)
    return files


def _ensure_cpu_mesh_env():
    """Graph lint wants >= 8 devices; must run before jax is imported.
    A no-op when jax is already loaded (tests: conftest did this)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpulint",
        description="dlrover_tpu static analysis: framework AST lint + "
                    "SPMD graph lint",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs for the AST pass (default: the "
                        "dlrover_tpu package)")
    p.add_argument("--baseline", default="",
                   help="baseline JSON (default: the checked-in "
                        "dlrover_tpu/analysis/baseline.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current AST findings "
                        "and exit 0 (ratchet reset — review the diff!)")
    p.add_argument("--ast-only", action="store_true",
                   help="skip the graph pass (pure-python, sub-second)")
    p.add_argument("--graph-only", action="store_true",
                   help="skip the AST pass")
    p.add_argument("--no-moe-audit", action="store_true",
                   help="graph pass on the dense model only (skips the "
                        "four MoE dispatch compiles, ~20s saved)")
    p.add_argument("--rules", default="",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--changed", nargs="?", const="<merge-base>",
                   default=None, metavar="REF",
                   help="incremental mode: AST+concurrency rules only "
                        "on .py files changed vs REF (default: the "
                        "merge-base with main); the graph/audit suite "
                        "and the stale-entry ratchet are skipped — a "
                        "sub-second pre-commit loop, not the CI gate")
    p.add_argument("--tol", type=float, default=0.0,
                   help="override the G106 collective-audit tolerance "
                        "factor")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    _ensure_cpu_mesh_env()
    args = build_parser().parse_args(argv)

    import dlrover_tpu
    from dlrover_tpu.analysis import ast_rules, findings as fmod

    pkg_dir = os.path.dirname(os.path.abspath(dlrover_tpu.__file__))
    root = os.path.dirname(pkg_dir)
    baseline_path = args.baseline or os.path.join(
        pkg_dir, "analysis", "baseline.json"
    )
    rules = set(r.strip() for r in args.rules.split(",") if r.strip()) \
        or None
    if args.write_baseline and (rules or args.paths or args.graph_only
                                or args.changed is not None):
        # the baseline is the FULL AST allowlist: regenerating it from a
        # rule subset or a path subset would silently drop every other
        # entry, and --graph-only has no baseline to write at all
        print("--write-baseline regenerates the whole allowlist: run it "
              "without --rules/--graph-only/--changed and without "
              "explicit paths",
              file=sys.stderr)
        return 2
    changed_mode = args.changed is not None
    if changed_mode:
        changed = _changed_files(root, args.changed)
        if changed is None:
            print("--changed: git could not resolve the diff ref; "
                  "run the full lint instead", file=sys.stderr)
            return 2
        # same scope as the full run: the package, not tests/tools —
        # the incremental loop must never be stricter than the gate
        changed = [f for f in changed
                   if f.startswith(pkg_dir + os.sep)]
        if not changed:
            print("0 changed .py files; nothing to lint")
            return 0
    # a --rules subset naming no DLR/G rule makes the matching pass a
    # guaranteed no-op; skip it (the graph pass costs five compiles)
    run_ast = not args.graph_only and (
        rules is None or any(r.startswith("DLR") for r in rules)
    )
    run_graph = not args.ast_only and not changed_mode and (
        rules is None or any(r.startswith("G") for r in rules)
    )

    all_findings = []
    stale: List[str] = []
    suppressed: Dict[str, int] = {}

    if run_ast:
        from dlrover_tpu.analysis import concurrency

        if changed_mode:
            paths = changed
        else:
            paths = args.paths or [pkg_dir]
        ast_findings = ast_rules.lint_paths(
            paths, root=root, rules=rules, counters=suppressed)
        # the concurrency pass shares the findings/baseline currency;
        # in --changed mode its lock graph spans only the changed
        # files (documented trade for the sub-second loop)
        ast_findings.extend(concurrency.lint_paths_concurrency(
            paths, root=root, rules=rules, counters=suppressed))
        ast_findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
        baseline = fmod.Baseline.load(baseline_path)
        new, stale = baseline.filter(ast_findings)
        if args.paths or rules is not None or changed_mode:
            # partial scope (explicit paths / a rule subset / changed
            # files): entries for the unscanned remainder naturally
            # consume no budget — that is not staleness, so the
            # ratchet only runs full-scope
            stale = []
        if args.write_baseline:
            fresh = fmod.Baseline.from_findings(ast_findings)
            # per-entry rationale survives a regeneration for keys
            # that still exist
            fresh.notes = {k: v for k, v in baseline.notes.items()
                           if k in fresh.entries}
            fresh.save(baseline_path)
            print(f"wrote {baseline_path} with "
                  f"{len(ast_findings)} entries")
            return 0
        all_findings.extend(new)

    reports = []
    if run_graph:
        from dlrover_tpu.analysis import graph_lint

        import jax

        jax.config.update("jax_platforms", "cpu")
        tol = args.tol or graph_lint.DEFAULT_AUDIT_TOL
        reports.append(graph_lint.lint_train_step(
            rules=rules, audit_tol=tol
        ))
        # the four-dispatch MoE sweep exists for the G106 byte audit;
        # a rule subset without G106 makes those compiles pure waste
        if not args.no_moe_audit and (rules is None or "G106" in rules):
            reports.extend(graph_lint.moe_dispatch_audit(
                rules=rules, audit_tol=tol
            ))
        # the quantization-drift probe rides the same gate: it is the
        # numerics face of the moe audit (G109 — the quantized program
        # vs its bf16 twin on a fixed probe batch, judged against the
        # ratcheted quant_baseline.json)
        if not args.no_moe_audit and (rules is None or "G109" in rules):
            # the only graph pass that EXECUTES a program: a host that
            # cannot run it (too few devices, broken backend) skips the
            # probe with a warning instead of killing the whole lint
            # run and the findings already computed
            # "kv" probes the serving tier's int8 page storage — the
            # ONLY family whose quantized format is int8, not fp8
            for family in ("moe", "fsdp", "grad", "kv"):
                try:
                    reports.append(graph_lint.quantization_drift_audit(
                        family=family,
                        precision=("int8" if family == "kv"
                                   else "fp8")))
                except Exception as e:  # noqa: BLE001
                    import logging

                    logging.getLogger("dlrover_tpu.analysis").warning(
                        "quantization drift probe (%s) skipped",
                        family, exc_info=True)
                    print(f"quantization drift probe ({family}) "
                          f"skipped: {type(e).__name__}: {e}")
        # serving-program audit: decode/prefill/page-copy compiled
        # programs checked for the gather-free KV read invariant
        # (G110) plus donation (G105) and weak-type hazards (G103)
        if not args.no_moe_audit and (
                rules is None
                or {"G110", "G105", "G103"}.intersection(rules)):
            try:
                reports.extend(graph_lint.serving_program_audit(
                    rules=rules))
            except Exception as e:  # noqa: BLE001
                import logging

                logging.getLogger("dlrover_tpu.analysis").warning(
                    "serving program audit skipped", exc_info=True)
                print(f"serving program audit skipped: "
                      f"{type(e).__name__}: {e}")
        for rep in reports:
            all_findings.extend(rep.findings)

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in all_findings],
            "stale_baseline_keys": stale,
            "suppressed": suppressed,
            "graph_reports": [
                {
                    "label": r.label,
                    "measured_bytes": r.measured_bytes,
                    "predicted_bytes": r.predicted_bytes,
                    "build_seconds": round(r.build_seconds, 2),
                }
                for r in reports
            ],
        }, indent=2))
    else:
        for f in all_findings:
            print(f.render())
        for rep in reports:
            ratio = rep.measured_total / max(rep.predicted_total, 1.0)
            print(
                f"graph {rep.label}: {len(rep.findings)} findings, "
                f"{rep.measured_total / 1e6:.2f} MB collectives vs "
                f"{rep.predicted_total / 1e6:.2f} MB predicted "
                f"(ratio {ratio:.2f}x) in {rep.build_seconds:.1f}s"
            )
        for key in stale:
            print(f"stale baseline entry (site fixed — remove it): {key}")
        n = len(all_findings)
        supp_note = ""
        if suppressed:
            total = sum(suppressed.values())
            detail = ", ".join(f"{k}×{suppressed[k]}"
                               for k in sorted(suppressed))
            supp_note = (f", {total} inline-suppressed ({detail})")
        print(f"{n} finding{'s' if n != 1 else ''} outside the baseline"
              + (f", {len(stale)} stale baseline entries" if stale
                 else "") + supp_note)
    if stale and not all_findings:
        # ratchet down: fixing a site must shrink the allowlist in the
        # same change, or the key masks the next regression there
        return 1
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())
