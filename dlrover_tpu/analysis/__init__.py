"""Static analysis for the framework and for compiled SPMD programs.

Two passes, one gate (ISSUE 2):

* ``ast_rules`` — rule-based lint over the ``dlrover_tpu`` sources for
  distributed-correctness pitfalls (RPCs without deadlines, swallowed
  exceptions on failover paths, non-daemon control threads, host
  impurity inside jit, shared mutable defaults).
* ``concurrency`` — the whole-package lock-discipline pass (ISSUE 17):
  inferred guard discipline per class (DLR010 mixed-guard access), a
  cross-class lock-acquisition graph with cycle detection (DLR011
  lock-order inversion), and blocking-calls-under-lock (DLR009 —
  sleeps, joins, un-timed queue ops, RPC verbs, device syncs, listener
  iteration). ``# guarded-by:`` annotations declare external
  discipline; ``# dlrlint: disable=DLR0xx <reason>`` suppresses inline
  (a reason-less disable is itself DLR012).
* ``graph_lint`` — SPMD lint of the lowered/compiled train step via the
  same ``accelerate()``/AOT path production uses: host callbacks,
  recompile hazards, dtype drift, dropped donation, silently replicated
  params, the planner-vs-HLO collective byte audit
  (``parallel.planner.predicted_collective_bytes``), and the serving
  program audit (G110 gather-free KV reads + donation/weak-type checks
  on the compiled decode/prefill/page-copy programs).

Run it: ``python -m dlrover_tpu.analysis`` (alias: ``tpulint``,
``tpurun lint``). Keep it green: ``tests/test_lint_clean.py`` runs the
AST pass in tier-1; the checked-in ``baseline.json`` allowlists legacy
sites and ratchets down as they are fixed.

This package must stay import-light (no jax at module scope): the CLI
configures the virtual CPU mesh before jax loads.
"""

from dlrover_tpu.analysis.findings import Baseline, Finding  # noqa: F401
