"""SPMD graph lint: check the program XLA will run against the plan the
planner priced.

The pass reuses the ``accelerate()`` build + ``lower()``/``compile()``
path of ``parallel.aot`` — the same artifacts the AOT fit-proof reads —
and checks invariants on three layers:

  StableHLO (pre-partitioning)   G102 host callbacks, G104 dtype drift
  lowering metadata              G103 weak-type (recompile-hazard) inputs
  optimized per-device HLO       G101 unintended full-parameter
                                 all-gathers / silently replicated
                                 params, G105 donation actually applied,
                                 G106 planner-vs-HLO collective byte
                                 audit

Rule ids:

  G101 sharded-strategy, replicated reality (or a hoisted full gather)
  G102 host callback inside the jitted step
  G103 weak-type python-scalar argument (recompiles on every new value)
  G104 dtype drift: f32 matmuls on a bf16 compute path
  G105 donation not applied to the train state
  G106 actual HLO collective bytes vs ``planner.predicted_collective_bytes``
  G107 compiled peak HBM above the configured per-device budget
  G108 serialized large collective: result consumed with no independent
       compute scheduled between issue and use (an overlap opportunity)

Every check is a pure function over lowered/compiled text so the AOT CLI
(``parallel.aot --lint``) and golden-fixture tests reuse them without
rebuilding models.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from dlrover_tpu.analysis.findings import Finding
from dlrover_tpu.common.log import get_logger

logger = get_logger("analysis.graph")

ALL_GRAPH_RULES = ("G101", "G102", "G103", "G104", "G105", "G106",
                   "G107", "G108", "G109", "G110")

GRAPH_RULE_DOCS: Dict[str, str] = {
    "G101": "params the strategy shards are replicated in the compiled "
            "program, or one all-gather re-materializes the full "
            "parameter set",
    "G102": "host callback (pure_callback/io_callback/debug.print) "
            "inside the jitted train step",
    "G103": "weak-type python-scalar argument — recompiles on every "
            "distinct value",
    "G104": "f32 dot_generals dominate a bf16 compute path (dtype drift)",
    "G105": "buffer donation not applied to the train state",
    "G106": "compiled HLO collective bytes diverge from the planner's "
            "predicted collective bytes beyond tolerance",
    "G107": "compiled peak HBM residency exceeds the configured "
            "per-device budget",
    "G108": "a large collective's result is consumed with no "
            "independent compute between issue and use — the network "
            "sits on the critical path (overlap opportunity)",
    "G109": "a quantized program's output drifts from its bf16 twin "
            "beyond the ratcheted per-model baseline (numerics "
            "regression)",
    "G110": "a gather on the KV read path of a compiled serving "
            "program (decode/prefill/page-copy must read the pool "
            "with slices, never a gather over pages)",
}

# G108: collectives below this output size are not worth overlapping
# (latency-bound, not bandwidth-bound) — and the CPU-mesh test fixtures
# all sit far below it, so the rule stays clean on HEAD while firing on
# real serial exchanges (the committed fixture is sized above it).
G108_MIN_BYTES = 1 << 20

# ops that count as INDEPENDENT work the scheduler could have run under
# an in-flight collective: fused compute, bare dots/convs, kernels
# (custom-call), and counted loops (which contain compute)
_G108_COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call",
                     "while")

# Default G106 tolerance (ratio, symmetric in log space). Chosen as one
# power of two above the worst measured-vs-predicted ratio observed on
# the HEAD fixtures (~16.7x for the einsum capacity dispatch, whose
# [T,E,C] one-hot movement GSPMD realizes as per-layer all-gathers the
# cost model prices as compute) — so the audit tolerates GSPMD's
# discretion and per-device-vs-per-link accounting slop, while a
# dropped, double-counted or mis-scaled cost term (the regression tests
# perturb terms 100-10000x) fails loudly. See docs/static_analysis.md.
DEFAULT_AUDIT_TOL = 32.0

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                     "all-to-all", "collective-permute")

_CALLBACK_TARGETS = re.compile(
    r"custom_call\s*@(\w*callback\w*|xla_ffi_python\w*)", re.IGNORECASE
)


def _balanced_block(text: str, marker: str) -> str:
    """The brace-balanced block opened by ``marker`` ('' if absent) —
    alias maps nest braces (``{0}: (0, {1}, may-alias)``), so a lazy
    regex would stop at the first ``}``."""
    start = text.find(marker)
    if start < 0:
        return ""
    i = start + len(marker)
    depth = 1
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[start + len(marker):i - 1]


def _shapes_bytes(fragment: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape in an HLO fragment."""
    total = 0
    for m in re.finditer(r"\b(\w+)\[([\d,]*)\]", fragment):
        dt = _DTYPE_BYTES.get(m.group(1))
        if dt is None:
            continue
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * dt
    return total


def _max_shape_bytes(fragment: str) -> int:
    """Bytes of the LARGEST single ``dtype[dims]`` shape in an HLO
    fragment — the payload estimate for async ``-start`` ops, whose
    tuple shape carries BOTH the operand and result buffers (summing
    the members would double-count the traffic)."""
    best = 0
    for m in re.finditer(r"\b(\w+)\[([\d,]*)\]", fragment):
        dt = _DTYPE_BYTES.get(m.group(1))
        if dt is None:
            continue
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        best = max(best, n * dt)
    return best


def _computations(optimized_hlo: str) -> Dict[str, str]:
    """HLO computation name -> body text. Headers sit at column 0
    (``%region_1.22 (...) -> ... {`` / ``ENTRY %main (...) -> ... {``),
    bodies are indented, ``}`` at column 0 closes."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    for line in optimized_hlo.splitlines():
        if (not line.startswith((" ", "}")) and "{" in line
                and "(" in line and "->" in line):
            name = line.split(" (", 1)[0]
            if name.startswith("ENTRY "):
                name = name[len("ENTRY "):]
            cur = name.strip()
            comps[cur] = []
        elif line.startswith("}"):
            cur = None
        elif cur is not None:
            comps[cur].append(line)
    return {k: "\n".join(v) for k, v in comps.items()}


_WHILE_BODY_RE = re.compile(r"\bbody=(%[\w.\-]+)")
_TRIP_COUNT_RE = re.compile(r'known_trip_count\\?":\{\\?"n\\?":\\?"(\d+)')


def _loop_multipliers(comps: Dict[str, str]) -> Dict[str, int]:
    """Execution multiplier per computation: a while body's ops run
    trip-count times (nested loops multiply). XLA annotates counted
    loops — every ``lax.scan``, in particular the scan-over-layers every
    production model here uses — with ``known_trip_count`` on the while
    op; an unannotated while conservatively counts once (today's
    behavior for genuinely dynamic loops)."""
    parent: Dict[str, Tuple[str, int]] = {}  # body -> (enclosing, trip)
    for name, text in comps.items():
        for line in text.splitlines():
            if " while(" not in line:
                continue
            body = _WHILE_BODY_RE.search(line)
            if not body:
                continue
            trip = _TRIP_COUNT_RE.search(line)
            parent[body.group(1)] = (
                name, int(trip.group(1)) if trip else 1
            )

    mult: Dict[str, int] = {}

    def resolve(name: str, seen=()) -> int:
        if name in mult:
            return mult[name]
        if name not in parent or name in seen:
            return 1
        enclosing, trip = parent[name]
        mult[name] = trip * resolve(enclosing, seen + (name,))
        return mult[name]

    return {name: resolve(name) for name in comps}


def collective_bytes_by_kind(optimized_hlo: str) -> Dict[str, int]:
    """Per-device bytes moved by each collective kind in one step.

    Parses the optimized (post-SPMD-partitioning) HLO: each op line's
    *output* shape is what this device receives, weighted by the
    enclosing while-loops' trip counts (``_loop_multipliers``) — a TP
    allreduce inside the 32-layer scan body moves 32x its textual
    bytes, which is what the planner's per-layer terms price. ``-done``
    halves of async pairs are skipped so starts aren't double-counted.
    """
    out: Dict[str, int] = {}
    # shape is non-greedy .+?: the TPU backend emits TUPLE-shaped
    # collectives — "(f32[..]{..:T(8,128)}, f32[..]) all-reduce(" — whose
    # shape list contains spaces; _shapes_bytes then sums every member
    pat = re.compile(
        r"^\s*%?\S+ = (.+?) ("
        + "|".join(_COLLECTIVE_KINDS)
        + r")(-start)?\(", re.MULTILINE
    )
    comps = _computations(optimized_hlo)
    mult = _loop_multipliers(comps)
    for name, text in comps.items():
        for m in pat.finditer(text):
            out[m.group(2)] = (
                out.get(m.group(2), 0)
                + _shapes_bytes(m.group(1)) * mult.get(name, 1)
            )
    return out


def max_allgather_bytes(optimized_hlo: str) -> int:
    """Largest single all-gather output (bytes) in the step."""
    best = 0
    pat = re.compile(r"^\s*%?\S+ = (.+?) all-gather(-start)?\(",
                     re.MULTILINE)
    for m in pat.finditer(optimized_hlo):
        best = max(best, _shapes_bytes(m.group(1)))
    return best


# -- individual checks (pure functions over artifacts) ----------------------


def check_host_callbacks(stablehlo: str,
                         path: str = "<train_step>") -> List[Finding]:
    findings = []
    targets = sorted({m.group(1) for m in
                      _CALLBACK_TARGETS.finditer(stablehlo)})
    for t in targets:
        findings.append(Finding(
            rule_id="G102", path=path, line=0,
            message=f"host callback `{t}` lowered inside the jitted "
                    f"step: every invocation synchronizes device->host, "
                    f"serializing the step and deadlocking under SPMD "
                    f"if any peer skips it",
            fixit="move the callback out of the step (metrics ride the "
                  "step outputs), or gate debug prints behind a "
                  "config flag that stays off in production",
        ))
    return findings


def check_weak_type_inputs(args_info: Any,
                           path: str = "<train_step>") -> List[Finding]:
    """``lowered.args_info`` -> findings for weak-typed scalar args."""
    import jax

    findings = []
    for leaf in jax.tree.leaves(args_info,
                                is_leaf=lambda x: hasattr(x, "_aval")
                                or hasattr(x, "aval")):
        aval = getattr(leaf, "aval", None) or getattr(leaf, "_aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            findings.append(Finding(
                rule_id="G103", path=path, line=0,
                message=f"argument traced from a python scalar "
                        f"(weak-type {aval}): jit re-compiles for every "
                        f"distinct value — the classic per-step "
                        f"learning-rate recompile",
                fixit="wrap host scalars in jnp.asarray(...) (strong "
                      "dtype) before passing them into the step",
            ))
    return findings


def check_dtype_drift(stablehlo: str, compute_dtype: str,
                      path: str = "<train_step>",
                      max_f32_frac: float = 0.5) -> List[Finding]:
    """On a bf16 compute path, most dots must be bf16.

    A tolerated f32 minority covers the blessed exceptions (f32 logits /
    loss reductions, optimizer math); crossing ``max_f32_frac`` means
    params or activations are being silently upcast — the full matmul
    cost of the precision you thought you were saving.
    """
    if compute_dtype not in ("bfloat16", "bf16", "float16", "f16"):
        return []
    dots = re.findall(
        r"stablehlo\.dot_general.*?->\s*tensor<[^>]*x(\w+)>", stablehlo
    )
    if not dots:
        dots = re.findall(r"dot_general[^\n]*\btensor<[^>]*x(\w+)>",
                          stablehlo)
    if not dots:
        return []
    f32 = sum(1 for d in dots if d in ("f32", "f64"))
    frac = f32 / len(dots)
    if frac > max_f32_frac:
        return [Finding(
            rule_id="G104", path=path, line=0,
            message=f"{f32}/{len(dots)} dot_generals compute in f32 on a "
                    f"{compute_dtype} path ({frac:.0%} > "
                    f"{max_f32_frac:.0%}): activations or params are "
                    f"being silently upcast",
            fixit="check model compute_dtype plumbing and optimizer "
                  "dtype casts; only the logits/loss tail should be f32",
        )]
    return []


def check_donation(optimized_hlo: str, n_state_leaves: int,
                   path: str = "<train_step>",
                   min_frac: float = 0.5) -> List[Finding]:
    """Donated state must actually alias: each aliased pair reuses an
    input buffer for an output, halving peak param+optimizer residency.
    XLA silently DROPS donation on dtype/shape/layout mismatch (it only
    warns), so absence here is a real memory regression, not a style
    issue."""
    block = _balanced_block(optimized_hlo, "input_output_alias={")
    aliased = len(re.findall(r"\(\s*\d+\s*,", block))
    need = max(1, int(n_state_leaves * min_frac))
    if aliased < need:
        return [Finding(
            rule_id="G105", path=path, line=0,
            message=f"donation not applied: {aliased} aliased buffers "
                    f"for a train state of {n_state_leaves} leaves "
                    f"(expected >= {need}) — peak memory pays params + "
                    f"optimizer state twice",
            fixit="jit the step with donate_argnums=(0,) and keep "
                  "input/output state dtypes+shapes identical so XLA "
                  "can alias them",
        )]
    return []


def check_param_shardings(state_sharding: Any, abstract_state: Any,
                          mesh_plan: Any,
                          path: str = "<train_step>",
                          rel_frac: float = 1 / 64) -> List[Finding]:
    """A strategy with model axes >1 must actually shard its big params.

    Catches sharding-rule/param-tree mismatches: ``tree_shardings``
    falls back to replicated when no rule matches a path, which
    silently costs fsdp-times the param memory and a full-parameter
    gather per step. "Big" is RELATIVE — bytes >= ``rel_frac`` of the
    total parameter bytes — because every sane rule set deliberately
    replicates the small per-layer tensors (norm scales, biases), and
    an absolute element threshold misfires on them the moment layers
    are stacked (a 32-layer llama's norm scales are 131k elems and
    0.004% of the params)."""
    import jax

    sizes = dict(mesh_plan.axis_sizes()) if hasattr(
        mesh_plan, "axis_sizes") else {}
    model_par = max(sizes.get("fsdp", 1), 1) * max(
        sizes.get("tensor", 1), 1) * max(sizes.get("pipe", 1), 1)
    if model_par <= 1:
        return []
    findings = []
    leaves = list(zip(
        jax.tree_util.tree_leaves_with_path(state_sharding.params),
        jax.tree.leaves(abstract_state.params),
    ))
    total_bytes = sum(a.size * a.dtype.itemsize for _, a in leaves)
    min_bytes = max(total_bytes * rel_frac, 1024)
    for (keypath, sharding), aval in leaves:
        if aval.size * aval.dtype.itemsize < min_bytes:
            continue
        if getattr(sharding, "is_fully_replicated", False):
            name = jax.tree_util.keystr(keypath)
            findings.append(Finding(
                rule_id="G101", path=path, line=0,
                message=f"param {name} ({aval.shape}, {aval.size} elems) "
                        f"is fully replicated although the strategy "
                        f"declares model-parallel degree {model_par}: "
                        f"no sharding rule matched this path",
                fixit="add a rule for this param path to the strategy's "
                      "rule set (parallel/sharding_rules.py)",
            ))
    return findings[:8]


def check_full_param_gather(optimized_hlo: str, total_param_bytes: int,
                            path: str = "<train_step>",
                            frac: float = 0.6) -> List[Finding]:
    """One all-gather whose output is ~the whole parameter set = XLA
    hoisted the fsdp gather out of the layer loop. Bounded above as well:
    a single *param* gather can produce at most total_param_bytes, so a
    bigger gather is activation movement (e.g. the capacity-MoE one-hot
    tensors) priced elsewhere — G106's business, not G101's."""
    biggest = max_allgather_bytes(optimized_hlo)
    if (total_param_bytes > 0
            and total_param_bytes * frac <= biggest
            <= total_param_bytes * 1.25):
        return [Finding(
            rule_id="G101", path=path, line=0,
            message=f"one all-gather re-materializes "
                    f"{biggest / 1e6:.1f} MB (> {frac:.0%} of the "
                    f"{total_param_bytes / 1e6:.1f} MB parameter set) on "
                    f"every device: XLA hoisted a full-parameter gather "
                    f"out of the layer loop",
            fixit="check donation + sharding specs; a scan-over-layers "
                  "model should gather at most one layer's params at "
                  "a time",
        )]
    return []


def collective_audit(measured_total: float, predicted_total: float,
                     tol: float = DEFAULT_AUDIT_TOL,
                     path: str = "<train_step>",
                     detail: str = "") -> List[Finding]:
    """G106: the compiled program's collective bytes must be within a
    (log-symmetric) factor ``tol`` of what the planner priced.

    Too-high means XLA inserted traffic the cost model does not price
    (plan/graph divergence — the planner is ranking meshes on fiction);
    too-low means the model overprices and will veto good plans. Skipped
    when the prediction is below 1 KiB (single-chip / degenerate mesh:
    scalar-reduction noise would dominate the ratio).
    """
    if predicted_total < 1024:
        return []
    measured_total = max(measured_total, 1.0)
    ratio = measured_total / predicted_total
    if 1.0 / tol <= ratio <= tol:
        return []
    direction = (
        "collectives the cost model does not price (plan/graph "
        "divergence)" if ratio > tol else
        "far less traffic than priced (the cost model overprices this "
        "mesh and will veto good plans)"
    )
    return [Finding(
        rule_id="G106", path=path, line=0,
        message=f"compiled HLO moves {measured_total / 1e6:.2f} MB of "
                f"collectives vs {predicted_total / 1e6:.2f} MB "
                f"predicted (ratio {ratio:.1f}x, tolerance {tol:g}x): "
                f"{direction}" + (f" [{detail}]" if detail else ""),
        fixit="re-derive the planner term for this mesh "
              "(parallel/planner.py predicted_collective_bytes) or fix "
              "the sharding rules producing the extra movement",
    )]


def check_serialized_collectives(
    optimized_hlo: str,
    path: str = "<train_step>",
    min_bytes: int = G108_MIN_BYTES,
    max_findings: int = 4,
) -> List[Finding]:
    """G108: a large collective whose result is consumed with NO
    independent compute between issue and first use — the op-order
    rendering of "the network sits on the critical path". The compiled
    HLO's textual op order follows the schedule (def before use), so
    zero compute ops between a collective (or its ``-start``) and the
    first line referencing its result means the scheduler had nothing
    to hide the exchange under: a chunked/double-buffered formulation
    (``ops.moe`` dispatch_chunks, the FSDP layer prefetch) is the fix
    this tree ships. Collectives under ``min_bytes`` are skipped —
    latency-bound traffic isn't worth restructuring, and the tolerance
    keeps the rule clean on the CPU-mesh fixtures."""
    findings: List[Finding] = []
    op_re = re.compile(r"^\s*(%?[\w.\-]+) = (.+?) ([\w\-]+)\(")
    for comp_name, body in _computations(optimized_hlo).items():
        lines = body.splitlines()
        parsed = [op_re.match(ln) for ln in lines]
        for i, m in enumerate(parsed):
            if m is None:
                continue
            name, shape, opcode = m.group(1), m.group(2), m.group(3)
            is_start = opcode.endswith("-start")
            base = opcode[:-len("-start")] if is_start else opcode
            if base not in _COLLECTIVE_KINDS or opcode.endswith("-done"):
                continue
            # a -start op's tuple shape holds operand AND result
            # buffers: size by the largest member, not the sum
            nbytes = (_max_shape_bytes(shape) if is_start
                      else _shapes_bytes(shape))
            if nbytes < min_bytes:
                continue
            token = re.compile(re.escape(name) + r"\b")
            independent = 0
            use_line = None
            for j in range(i + 1, len(lines)):
                if token.search(lines[j]):
                    use_line = j
                    break
                pj = parsed[j]
                if pj is not None and pj.group(3) in _G108_COMPUTE_OPS:
                    independent += 1
            if use_line is None or independent > 0:
                continue
            findings.append(Finding(
                rule_id="G108", path=path, line=0,
                message=f"{base} ({nbytes / 1e6:.1f} MB, {name} in "
                        f"{comp_name}) is consumed immediately — no "
                        f"independent compute between issue and use, "
                        f"so the exchange sits fully exposed on the "
                        f"critical path",
                fixit="restructure for overlap: chunk the exchange and "
                      "double-buffer it under compute (ops/moe.py "
                      "dispatch_chunks, the ops/ring.py ppermute ring) "
                      "or prefetch the gather a layer ahead "
                      "(fsdp_prefetch)",
            ))
            if len(findings) >= max_findings:
                return findings
    return findings


# G109: how far above its committed baseline a model's quantization
# drift may grow before the lint fires. The baseline is the drift
# MEASURED at commit time (quant_baseline.json, per model label) — the
# ratchet mirrors the AST baseline's discipline: today's numerics are
# the contract, and a change that doubles the drift is a regression to
# explain, not to absorb silently. 4x leaves room for routing jitter
# across probe batches; an fp8 path gone wrong (scale bug, double
# quantization, a dequant in the wrong dtype) moves drift by orders of
# magnitude, not fractions.
G109_DRIFT_RATIO = 4.0
# the absolute floor under which drift differences are noise (f32
# accumulation order), and the default tolerance when a model has no
# committed baseline entry yet
G109_DRIFT_FLOOR = 1e-5
G109_DEFAULT_TOL = 0.02


def check_quantization_drift(measured_drift: float,
                             baseline_drift: Optional[float],
                             ratio: float = G109_DRIFT_RATIO,
                             path: str = "<train_step>",
                             detail: str = "") -> List[Finding]:
    """G109: the relative output drift of a quantized program against
    its bf16 twin (same params, same probe batch) must stay within the
    ratcheted per-model baseline — ``baseline * ratio``, floored so a
    near-zero committed baseline cannot make reassociation noise fire.
    ``baseline_drift=None`` (no committed entry) falls back to the
    absolute default tolerance. The G104 extension the low-precision
    paths needed: G104 catches dtype drift in the PROGRAM (f32 dots on
    a bf16 path); G109 catches drift in the NUMBERS (a quantization
    regression the graph text cannot show)."""
    if baseline_drift is None:
        tol = G109_DEFAULT_TOL
        basis = f"default tolerance {G109_DEFAULT_TOL:g} (no baseline)"
    else:
        tol = max(float(baseline_drift) * ratio, G109_DRIFT_FLOOR)
        basis = (f"baseline {baseline_drift:.3g} x {ratio:g} "
                 f"(floor {G109_DRIFT_FLOOR:g})")
    if measured_drift <= tol:
        return []
    return [Finding(
        rule_id="G109", path=path, line=0,
        message=f"quantized program drifts {measured_drift:.3g} "
                f"(relative) from its bf16 twin on the fixed probe "
                f"batch, above {basis}: the low-precision path's "
                f"numerics regressed"
                + (f" [{detail}]" if detail else ""),
        fixit="bisect the quantization path (ops/quantize.py encode, "
              "ops/grouped_matmul.py dequant-in-kernel, ops/moe.py "
              "wire boundary); if the drift increase is understood and "
              "acceptable, re-ratchet the model's entry in "
              "dlrover_tpu/analysis/quant_baseline.json",
    )]


def quantization_drift_baseline_path() -> str:
    import os

    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "quant_baseline.json")


def _probe_batch(config, global_batch: int, seed: int = 0):
    """The fixed, seeded probe batch every drift family shares."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(seed)
    seq = config.max_seq_len
    ids = rng.randint(0, config.vocab_size, size=(global_batch, seq + 1))
    return {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }


def _measure_fsdp_drift(config, precision: str, global_batch: int):
    """The "fsdp" family probe: one forward loss of the dense llama
    with the quantized per-layer gather wire vs its bf16 twin. The
    wire transform is elementwise over the stacked params (quantize
    commutes with the per-layer slice), so the drift is pure weight-
    qdq rounding and mesh-independent — the probe runs unsharded."""
    import dataclasses

    import jax

    from dlrover_tpu.models import llama

    if config is None:
        config = llama.llama_tiny(num_layers=4)
    batch = _probe_batch(config, global_batch)
    params = llama.init(jax.random.PRNGKey(0), config)

    def loss_at(prec: str) -> float:
        cfg = dataclasses.replace(config, fsdp_precision=prec)
        out = jax.jit(llama.make_loss_fn(cfg))(
            params, batch, jax.random.PRNGKey(1))
        loss = out[0] if isinstance(out, tuple) else out
        return float(jax.device_get(loss))

    loss_q = loss_at(precision)
    loss_b = loss_at("bf16")
    drift = abs(loss_q - loss_b) / max(abs(loss_b), 1e-12)
    label = f"llama_tiny[fsdp,{precision}]@{jax.default_backend()}"
    return drift, label


def _measure_grad_drift(config, precision: str, global_batch: int,
                        steps: int = 4, lr: float = 1e-2):
    """The "grad" family probe: a few deterministic SGD steps with the
    error-feedback quantized gradient path vs the exact bf16 twin,
    judged on the final loss. Single-program (no mesh): the transform
    is elementwise over the gradient tree, so the drift does not
    depend on how the grads were sharded."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import _apply_grad_wire

    if config is None:
        config = llama.llama_tiny(num_layers=2)
    batch = _probe_batch(config, global_batch)
    loss_fn = llama.make_loss_fn(_dc.replace(config))
    grad_fn = jax.value_and_grad(
        lambda p, b, r: loss_fn(p, b, r)[0])

    def step(params, residual, quantized):
        loss, grads = grad_fn(params, batch, jax.random.PRNGKey(1))
        new_residual = residual
        if quantized:
            # the probed mode must be the LABELED mode — "fp8_nofb"
            # measures the no-feedback control, not the EF path
            grads, new_residual = _apply_grad_wire(
                grads, residual, precision)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, new_residual, loss

    def run(quantized: bool) -> float:
        params = llama.init(jax.random.PRNGKey(0), config)
        residual = jax.tree.map(jnp.zeros_like, params)
        loss = None
        fn = jax.jit(lambda p, r: step(p, r, quantized))
        for _ in range(steps):
            params, residual, loss = fn(params, residual)
        return float(jax.device_get(loss))

    loss_q = run(True)
    loss_b = run(False)
    drift = abs(loss_q - loss_b) / max(abs(loss_b), 1e-12)
    label = f"llama_tiny[grad,{precision}]@{jax.default_backend()}"
    return drift, label


def _measure_kv_drift(config, precision: str, global_batch: int,
                      prompt_len: int = 12, decode_steps: int = 6):
    """The "kv" family probe: teacher-forced prefill+decode over the
    serving KV cache with quantized (int8) page storage vs the f32
    pool, judged on the mean next-token cross entropy of the decode
    steps. Single-slot, unsharded: the page encode/decode is
    elementwise per token vector, so the drift does not depend on how
    the pool was sharded."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.models import llama
    from dlrover_tpu.serving.kv_cache import (
        KVCacheSpec,
        init_kv_cache,
        resolve_kv_precision,
    )

    if resolve_kv_precision(precision) != precision:
        # the probe-fallback would silently run TWO f32 programs and
        # measure drift 0 against the ratchet — the fp8 families'
        # contract is to RAISE on an incapable host so the lint runner
        # skips the family with a warning instead of recording a
        # fiction (and the both-ways ratchet firing "improved")
        raise RuntimeError(
            f"kv drift probe: backend cannot run {precision!r} "
            "(capability probe failed)")
    if config is None:
        config = llama.llama_tiny()
    rng = np.random.RandomState(0)
    seq = rng.randint(0, config.vocab_size,
                      size=(prompt_len + decode_steps + 1,))

    def run(kvp: str) -> float:
        spec = KVCacheSpec.from_model(
            config, num_slots=2,
            max_seq=prompt_len + decode_steps + 1, page_size=8,
            precision=kvp)
        params = llama.init(jax.random.PRNGKey(0), config)
        cache = init_kv_cache(spec)
        cache, logits = llama.prefill_chunk(
            params, cache, jnp.asarray(seq[:prompt_len], jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.int32(prompt_len),
            config, spec)
        active = jnp.asarray([True, False])
        total = -jax.nn.log_softmax(logits)[seq[prompt_len]]
        for j in range(decode_steps):
            tokens = jnp.asarray([seq[prompt_len + j], 0], jnp.int32)
            _nt, step_logits, cache = llama.decode_step(
                params, cache, tokens, active, config, spec)
            total = total - jax.nn.log_softmax(
                step_logits[0])[seq[prompt_len + j + 1]]
        return float(jax.device_get(total)) / (decode_steps + 1)

    loss_q = run(precision)
    loss_b = run("f32")
    drift = abs(loss_q - loss_b) / max(abs(loss_b), 1e-12)
    label = f"llama_tiny[kv,{precision}]@{jax.default_backend()}"
    return drift, label


def measure_quantization_drift(config=None, precision: str = "fp8",
                               global_batch: int = 4,
                               family: str = "moe"):
    """(drift, label): the relative loss difference between the
    quantized program and its bf16-wire twin on a FIXED probe batch —
    same params, same routing seed, only the wire precision differs.
    Deterministic per backend (the probe is seeded and single-process),
    which is what lets the baseline ratchet instead of tolerance-guess.

    ``family`` selects which quantized boundary is probed; each knob
    family ratchets its OWN ``quant_baseline.json`` entry (fire/clean
    per family): "moe" (the grouped_ep row-exchange wire — the default
    and the PR 11 behavior), "fsdp" (the dense per-layer param-gather
    wire, ``_measure_fsdp_drift``) and "grad" (the error-feedback
    gradient path, ``_measure_grad_drift``).

    The "moe" model: the tiny grouped_ep MoE llama over an explicit
    4-way (data x fsdp) expert submesh — every quantized boundary
    (row quantize, exchange, dequant-in-kernel, return wire) executes.
    Runs on the HOST backend's devices (the probe needs to EXECUTE,
    unlike the deviceless byte audits)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.models import llama

    if family == "fsdp":
        return _measure_fsdp_drift(config, precision, global_batch)
    if family == "grad":
        return _measure_grad_drift(config, precision, global_batch)
    if family == "kv":
        return _measure_kv_drift(config, precision, global_batch)
    if family != "moe":
        raise ValueError(f"unknown drift family {family!r}")
    if config is None:
        # chunks pinned to 1: the probe must not resolve an ambient
        # Context chunk knob (drift is C-invariant — per-row outputs
        # are exact at any C — but the baseline label should name ONE
        # program shape)
        config = llama.llama_tiny(
            num_experts=8, moe_dispatch="grouped_ep", moe_top_k=2,
            moe_dispatch_chunks=1,
        )
    # 4-way when the host has it, else 2-way — never an odd count the
    # (n//2, 2) mesh reshape cannot tile (a 3-device host must probe
    # on 2, not crash)
    n = 4 if len(jax.devices()) >= 4 else 2
    if len(jax.devices()) < 2:
        raise RuntimeError(
            "quantization drift probe needs >= 2 devices for the "
            "expert submesh"
        )
    from jax.sharding import Mesh

    mesh = Mesh(
        np.array(jax.devices()[:n]).reshape(n // 2, 2),
        ("data", "fsdp"),
    )
    rng = np.random.RandomState(0)
    seq = config.max_seq_len
    ids = rng.randint(0, config.vocab_size, size=(global_batch, seq + 1))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    params = llama.init(jax.random.PRNGKey(0), config)

    def loss_at(prec: str) -> float:
        cfg = dataclasses.replace(config, mesh=mesh, moe_precision=prec)
        loss_fn = llama.make_loss_fn(cfg)
        out = jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(1))
        loss = out[0] if isinstance(out, tuple) else out
        return float(jax.device_get(loss))

    loss_q = loss_at(precision)
    loss_b = loss_at("bf16")
    drift = abs(loss_q - loss_b) / max(abs(loss_b), 1e-12)
    # the label carries the EXECUTING backend: drift is a property of
    # the kernels that ran (interpret-mode on cpu, Mosaic on tpu —
    # different accumulation/fusion orders), so a baseline ratcheted
    # on one backend must not judge another; a backend without an
    # entry falls back to the absolute default tolerance
    label = (f"llama_tiny_moe[grouped_ep,{precision}]"
             f"@{jax.default_backend()}")
    return drift, label


def quantization_drift_audit(config=None, precision: str = "fp8",
                             baseline_path: str = "",
                             ratio: float = G109_DRIFT_RATIO,
                             family: str = "moe",
                             ) -> GraphLintReport:
    """The G109 acceptance audit: run the quantized-vs-bf16 probe for
    one knob ``family`` ("moe" | "fsdp" | "grad") and judge the drift
    against the committed per-model, per-family baseline
    (``dlrover_tpu/analysis/quant_baseline.json``) — numerics
    regressions fail ``tpulint`` / ``aot --lint`` the way byte
    regressions (G106) already do."""
    import json
    import os

    t0 = time.time()
    drift, label = measure_quantization_drift(config, precision,
                                              family=family)
    path = baseline_path or quantization_drift_baseline_path()
    baseline_drift = None
    if os.path.exists(path):
        with open(path) as fh:
            entries = json.load(fh).get("entries", {})
        entry = entries.get(label)
        if entry is not None:
            baseline_drift = float(entry.get("drift", 0.0))
    report = GraphLintReport(label=label)
    report.findings = check_quantization_drift(
        drift, baseline_drift, ratio=ratio, path=label,
        detail=f"measured drift {drift:.3g}",
    )
    report.build_seconds = time.time() - t0
    logger.info(
        "quantization drift audit %s: drift %.3g vs baseline %s, "
        "%d findings, %.1fs", label, drift, baseline_drift,
        len(report.findings), report.build_seconds,
    )
    return report


def check_memory_budget(peak_hbm_bytes: float, hbm_budget_bytes: float,
                        path: str = "<train_step>") -> List[Finding]:
    """G107: the compiled program's peak HBM (``memory_analysis``:
    args + temps + outputs - donated aliases, per device) must fit the
    configured budget — the static-analysis face of the runtime
    optimizer's memory-feasibility gate, so an over-budget program
    fails ``aot.py --lint`` BEFORE a chip is allocated. Skipped when
    either side is unknown (<= 0)."""
    if peak_hbm_bytes <= 0 or hbm_budget_bytes <= 0:
        return []
    if peak_hbm_bytes <= hbm_budget_bytes:
        return []
    return [Finding(
        rule_id="G107", path=path, line=0,
        message=f"compiled peak HBM {peak_hbm_bytes / 1e9:.2f} GB "
                f"exceeds the per-device budget "
                f"{hbm_budget_bytes / 1e9:.2f} GB "
                f"({peak_hbm_bytes / hbm_budget_bytes:.2f}x): this "
                f"program OOMs the devices it claims to target",
        fixit="shard more (fsdp/tensor), raise remat, shrink the "
              "per-chip batch, or raise "
              "DLROVER_TPU_DEVICE_HBM_BUDGET_BYTES if the budget is "
              "deliberately conservative",
    )]


# -- drivers ----------------------------------------------------------------


@dataclass
class GraphLintReport:
    label: str
    findings: List[Finding] = field(default_factory=list)
    measured_bytes: Dict[str, int] = field(default_factory=dict)
    predicted_bytes: Dict[str, float] = field(default_factory=dict)
    build_seconds: float = 0.0

    @property
    def measured_total(self) -> int:
        return sum(self.measured_bytes.values())

    @property
    def predicted_total(self) -> float:
        return sum(self.predicted_bytes.values())


def lint_artifacts(
    *,
    stablehlo: str,
    optimized_hlo: str = "",
    args_info: Any = None,
    state_sharding: Any = None,
    abstract_state: Any = None,
    mesh_plan: Any = None,
    model_spec: Any = None,
    device_spec: Any = None,
    compute_dtype: str = "",
    total_param_bytes: int = 0,
    n_state_leaves: int = 0,
    rules: Optional[Set[str]] = None,
    audit_tol: float = DEFAULT_AUDIT_TOL,
    pipe_virtual: int = 1,
    steps_per_call: int = 1,
    peak_hbm_bytes: float = 0.0,
    hbm_budget_bytes: float = 0.0,
    label: str = "<train_step>",
) -> GraphLintReport:
    """Run every enabled graph rule over already-built artifacts (the
    shared entry for ``lint_train_step`` and ``parallel.aot --lint``).
    ``pipe_virtual`` must match what the caller's ``estimate()`` priced —
    the circular schedule multiplies the pipe handoff bytes by V.
    ``steps_per_call``: the multi-step fusion degree of the compiled
    program — the outer ``lax.scan`` carries ``known_trip_count=K``, so
    the measured collective bytes come out K-weighted by
    ``_loop_multipliers`` and the per-step planner prediction must be
    scaled by K to stay comparable (G106).
    ``peak_hbm_bytes``/``hbm_budget_bytes``: the compiled per-device
    residency and its budget for G107 (0 = skip the check)."""
    from dlrover_tpu.parallel import planner

    on = set(rules) if rules is not None else set(ALL_GRAPH_RULES)
    report = GraphLintReport(label=label)
    f = report.findings
    if "G102" in on:
        f.extend(check_host_callbacks(stablehlo, path=label))
    if "G103" in on and args_info is not None:
        f.extend(check_weak_type_inputs(args_info, path=label))
    if "G104" in on and compute_dtype:
        f.extend(check_dtype_drift(stablehlo, compute_dtype, path=label))
    if optimized_hlo:
        report.measured_bytes = collective_bytes_by_kind(optimized_hlo)
        if "G105" in on and n_state_leaves:
            f.extend(check_donation(optimized_hlo, n_state_leaves,
                                    path=label))
        if "G101" in on and total_param_bytes:
            f.extend(check_full_param_gather(
                optimized_hlo, total_param_bytes, path=label))
    if "G101" in on and state_sharding is not None and mesh_plan is not None:
        f.extend(check_param_shardings(
            state_sharding, abstract_state, mesh_plan, path=label))
    if ("G106" in on and optimized_hlo and mesh_plan is not None
            and model_spec is not None):
        report.predicted_bytes = planner.predicted_collective_bytes(
            mesh_plan, model_spec,
            device_spec or planner.TPU_SPECS["v5e"],
            pipe_virtual=pipe_virtual,
        )
        if steps_per_call > 1:
            report.predicted_bytes = {
                k: v * steps_per_call
                for k, v in report.predicted_bytes.items()
            }
        detail = ", ".join(
            f"{k}={v / 1e6:.2f}MB"
            for k, v in sorted(report.measured_bytes.items())
        )
        f.extend(collective_audit(
            report.measured_total, report.predicted_total,
            tol=audit_tol, path=label, detail=detail,
        ))
    if "G107" in on:
        f.extend(check_memory_budget(peak_hbm_bytes, hbm_budget_bytes,
                                     path=label))
    if "G108" in on and optimized_hlo:
        f.extend(check_serialized_collectives(optimized_hlo, path=label))
    return report


def lint_train_step(
    config=None,
    *,
    strategy=None,
    global_batch: int = 8,
    rules: Optional[Set[str]] = None,
    audit_tol: float = DEFAULT_AUDIT_TOL,
    devices=None,
    tpu_gen: str = "v5e",
    steps_per_call: int = 1,
    hbm_budget_bytes: float = 0.0,
    label: str = "",
) -> GraphLintReport:
    """Build (model, strategy) through ``accelerate``, lower + compile on
    the available devices, and lint the artifacts.

    ``steps_per_call`` > 1 lints the MULTI-step program
    (``train_step_multi``, the K-step ``lax.scan``) instead of the
    single step: donation (G105) must survive the outer scan and the
    G106 audit compares K-weighted measured bytes against a K-scaled
    prediction.

    Defaults to the bf16 ``llama_tiny`` on a data=2 x fsdp=2 x tensor=2
    mesh — small enough that the whole pass (build, lower, compile,
    checks) stays in single-digit seconds on a CPU host, while still
    exercising every collective family the planner prices.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel import planner
    from dlrover_tpu.parallel.accelerate import accelerate
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy

    t0 = time.time()
    if config is None:
        config = llama.llama_tiny(
            param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16
        )
    if strategy is None:
        n = len(devices) if devices is not None else len(jax.devices())
        if n >= 8:
            plan = MeshPlan(data=2, fsdp=2, tensor=2)
        elif n > 1:
            plan = MeshPlan(data=1, fsdp=n)
        else:
            plan = MeshPlan(data=1)
        rule = "moe_ep" if (config.num_experts > 0
                            and config.moe_dispatch == "grouped_ep") else (
            "moe" if config.num_experts > 0 else "llama")
        strategy = Strategy(mesh=plan, rule_set=rule)

    rng = np.random.RandomState(0)
    seq = config.max_seq_len
    ids = rng.randint(0, config.vocab_size, size=(global_batch, seq + 1))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    result = accelerate(
        llama.make_init_fn(config),
        llama.make_loss_fn(config),
        optax.adafactor(1e-3),
        batch,
        strategy=strategy,
        devices=devices,
        steps_per_call=steps_per_call,
    )
    abstract_state = jax.eval_shape(result.init_fn, jax.random.PRNGKey(0))
    if steps_per_call > 1:
        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                (steps_per_call,) + x.shape, x.dtype
            ), batch,
        )
        key = jax.ShapeDtypeStruct((steps_per_call, 2), jnp.uint32)
        step_fn = result.train_step_multi
    else:
        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch
        )
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        step_fn = result.train_step
    lowered = step_fn.lower(abstract_state, abstract_batch, key)
    compiled = lowered.compile()

    model_spec = planner.model_spec_from_llama(config, global_batch)
    param_bytes = sum(
        a.size * a.dtype.itemsize
        for a in jax.tree.leaves(abstract_state.params)
    )
    name = label or (
        f"llama_tiny[{config.moe_dispatch}]" if config.num_experts > 0
        else "llama_tiny"
    )
    if steps_per_call > 1 and not label:
        name += f"[K={steps_per_call}]"
    # G107 inputs: compiled residency via the shared memory shim, the
    # budget from the caller > Context knob > the device spec capacity
    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.utils.prof import compiled_peak_bytes

    budget = (
        hbm_budget_bytes
        or float(getattr(get_context(), "device_hbm_budget_bytes", 0.0))
        or float(planner.TPU_SPECS[tpu_gen].hbm_bytes)
    )
    report = lint_artifacts(
        stablehlo=lowered.as_text(),
        optimized_hlo=compiled.as_text(),
        args_info=getattr(lowered, "args_info", None),
        state_sharding=result.state_sharding,
        abstract_state=abstract_state,
        mesh_plan=strategy.mesh.resolve(
            len(devices) if devices is not None else len(jax.devices())
        ),
        model_spec=model_spec,
        device_spec=planner.TPU_SPECS[tpu_gen],
        compute_dtype=jnp.dtype(config.compute_dtype).name,
        total_param_bytes=param_bytes,
        n_state_leaves=len(jax.tree.leaves(abstract_state)),
        rules=rules,
        audit_tol=audit_tol,
        steps_per_call=steps_per_call,
        peak_hbm_bytes=float(compiled_peak_bytes(compiled)),
        hbm_budget_bytes=budget,
        label=name,
    )
    report.build_seconds = time.time() - t0
    logger.info(
        "graph lint %s: %d findings, %.2f MB measured vs %.2f MB "
        "predicted collectives, %.1fs",
        name, len(report.findings), report.measured_total / 1e6,
        report.predicted_total / 1e6, report.build_seconds,
    )
    return report


def moe_dispatch_audit(
    dispatches=("gather", "einsum", "grouped", "grouped_ep"),
    num_experts: int = 4,
    audit_tol: float = DEFAULT_AUDIT_TOL,
    rules: Optional[Set[str]] = None,
) -> List[GraphLintReport]:
    """The acceptance audit: compile tiny MoE models for every dispatch
    and check each compiled program's collective bytes against the
    planner terms (``moe_disp_*`` et al.) — cost-model drift on ANY
    dispatch fails the lint.

    The "einsum" REFERENCE ORACLE is exempt from G108: its one-hot
    [T,E,C] capacity movement is serialized by construction (GSPMD
    all-gathers consumed straight into the dispatch einsums) and the
    planner already prices it as quadratic COMPUTE, not comm — it
    exists to test against, never to run. G108's job is keeping the
    production paths (grouped_ep's chunked exchange, the fsdp
    gathers) overlapped; those stay fully covered."""
    from dlrover_tpu.models import llama

    reports = []
    for dispatch in dispatches:
        config = llama.llama_tiny(
            num_experts=num_experts, moe_dispatch=dispatch
        )
        dispatch_rules = rules
        if dispatch == "einsum":
            dispatch_rules = (
                set(rules) if rules is not None else set(
                    ALL_GRAPH_RULES)
            ) - {"G108"}
        reports.append(lint_train_step(
            config,
            rules=dispatch_rules,
            audit_tol=audit_tol,
            label=f"llama_tiny_moe[{dispatch}]",
        ))
    return reports


# -- G110: the serving-program audit ----------------------------------------

_HLO_DEF_RE = re.compile(r"%([\w.\-]+)\s*=\s*\(?\s*(\w+)\[([\d,]*)\]")


def check_kv_read_gather(optimized_hlo: str,
                         path: str = "<serve>",
                         min_rank: int = 4) -> List[Finding]:
    """No ``gather`` whose operand is a KV pool tensor (rank >=
    ``min_rank``) may survive compilation of a serving program.

    The slot-major pool exists so decode reads K/V with contiguous
    (dynamic-)slices; a gather over pages re-materializes the page
    table indirection on device — per-token random access at HBM
    latency on the hottest serving loop. Rank separates the pool
    (``[L, S, T, KV, HD]`` and its scale leaves, rank 4-5) from the
    benign rank-2 table gathers every program legitimately contains
    (token embeddings ``[V, D]``, rotary tables): firing on those
    would make the rule all-noise. The scan covers every computation
    body, so gathers fused into fusion computations are seen too."""
    # name -> rank, from every instruction definition in the module
    ranks: Dict[str, int] = {}
    for m in _HLO_DEF_RE.finditer(optimized_hlo):
        dims = m.group(3)
        ranks[m.group(1)] = len(dims.split(",")) if dims else 0
    findings: List[Finding] = []
    # first operand, either inline-typed (`gather(f32[2,8,..]{..} %x,`)
    # or bare (`gather(%x,`); the lookbehind keeps `all-gather(` — a
    # *collective*, not an indexed read — out of scope
    gather_re = re.compile(
        r"(?<![\w-])gather\(\s*(?:(\w+)\[([\d,]*)\]\S*\s+)?%([\w.\-]+)")
    for line in optimized_hlo.splitlines():
        gm = gather_re.search(line)
        if gm is None:
            continue
        operand = gm.group(3)
        if gm.group(1) is not None:
            # operand written inline with a shape: count its dims
            rank = len(gm.group(2).split(",")) if gm.group(2) else 0
        else:
            rank = ranks.get(operand, 0)
        if rank >= min_rank:
            findings.append(Finding(
                rule_id="G110", path=path, line=0,
                message=f"compiled program gathers from rank-{rank} "
                        f"operand `%{operand}`: a gather over the KV "
                        f"pool puts per-token random access on the "
                        f"decode hot path (the slot-major layout "
                        f"exists so reads are contiguous slices)",
                fixit="index pages with lax.dynamic_slice / "
                      "dynamic_update_slice keyed by slot+position; "
                      "keep page indirection on the host (the router "
                      "picks the slot, the program slices it)",
            ))
    return findings


def serving_program_audit(
    rules: Optional[Set[str]] = None,
    num_slots: int = 4,
    max_seq: int = 64,
    prefill_chunk: int = 16,
    spec_draft_len: int = 4,
) -> List[GraphLintReport]:
    """Compile the five serving programs exactly as ``ServeEngine.
    _compile`` does — ``decode_step`` / ``prefill_chunk`` (with the
    on-device first-token argmax) / speculative ``verify_step`` with
    the cache donated, the prefix page copies with their destination
    donated — and lint each: the gather-free KV read invariant (G110:
    for ``verify_step`` this covers the masked multi-token KV append,
    whose ``mode="drop"`` scatter rows must not reintroduce a pool
    gather), donation actually applied (G105: losing it doubles pool
    residency per dispatch), and weak-type scalar args (G103: a
    python-int slot id would recompile per slot). No mesh/shardings
    needed: the invariants are layout properties of the single-device
    program, and GSPMD only partitions the same op stream."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models import llama
    from dlrover_tpu.serving.kv_cache import (
        KVCacheSpec,
        copy_page_to_slot,
        copy_page_to_pool,
        init_kv_cache,
        init_prefix_pool,
    )

    config = llama.llama_tiny(param_dtype=jnp.bfloat16,
                              compute_dtype=jnp.bfloat16)
    spec = KVCacheSpec.from_model(
        config, num_slots=num_slots, max_seq=max_seq,
        prefix_pool_pages=4)
    params_abs = jax.eval_shape(
        lambda r: llama.init(r, config), jax.random.PRNGKey(0))
    cache_abs = jax.eval_shape(lambda: init_kv_cache(spec))
    pool_abs = jax.eval_shape(lambda: init_prefix_pool(spec))
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731

    def decode_fn(params, cache, tokens, active):
        return llama.decode_step(params, cache, tokens, active,
                                 config, spec)

    def prefill_fn(params, cache, tokens, slot, start, n_valid):
        cache, last_logits = llama.prefill_chunk(
            params, cache, tokens, slot, start, n_valid, config, spec)
        first = jnp.argmax(last_logits).astype(jnp.int32)
        return cache, last_logits, first

    def verify_fn(params, cache, tokens, active, n_draft):
        return llama.verify_step(params, cache, tokens, active,
                                 n_draft, config, spec)

    def admit_fn(cache, pool, slot, dst_start, src_page):
        return copy_page_to_slot(cache, pool, slot, dst_start,
                                 src_page, spec)

    def publish_fn(pool, cache, slot, src_start, dst_page):
        return copy_page_to_pool(pool, cache, slot, src_start,
                                 dst_page, spec)

    programs = [
        ("serve_decode",
         jax.jit(decode_fn, donate_argnums=(1,)),
         (params_abs, cache_abs, i32(num_slots),
          jax.ShapeDtypeStruct((num_slots,), jnp.bool_)),
         len(jax.tree.leaves(cache_abs))),
        ("serve_prefill",
         jax.jit(prefill_fn, donate_argnums=(1,)),
         (params_abs, cache_abs, i32(prefill_chunk), i32(), i32(),
          i32()),
         len(jax.tree.leaves(cache_abs))),
        ("serve_verify",
         jax.jit(verify_fn, donate_argnums=(1,)),
         (params_abs, cache_abs, i32(num_slots, spec_draft_len + 1),
          jax.ShapeDtypeStruct((num_slots,), jnp.bool_),
          i32(num_slots)),
         len(jax.tree.leaves(cache_abs))),
        ("serve_admit_copy",
         jax.jit(admit_fn, donate_argnums=(0,)),
         (cache_abs, pool_abs, i32(), i32(), i32()),
         len(jax.tree.leaves(cache_abs))),
        ("serve_publish_copy",
         jax.jit(publish_fn, donate_argnums=(0,)),
         (pool_abs, cache_abs, i32(), i32(), i32()),
         len(jax.tree.leaves(pool_abs))),
    ]
    on = set(rules) if rules is not None else set(ALL_GRAPH_RULES)
    reports = []
    for label, fn, abstract_args, n_donated in programs:
        t0 = time.time()
        lowered = fn.lower(*abstract_args)
        compiled = lowered.compile()
        hlo = compiled.as_text()
        report = GraphLintReport(label=label)
        if "G110" in on:
            report.findings.extend(
                check_kv_read_gather(hlo, path=label))
        if "G105" in on:
            report.findings.extend(check_donation(
                hlo, n_donated, path=label))
        if "G103" in on:
            report.findings.extend(check_weak_type_inputs(
                getattr(lowered, "args_info", None), path=label))
        report.build_seconds = time.time() - t0
        logger.info("serving audit %s: %d findings, %.1fs",
                    label, len(report.findings), report.build_seconds)
        reports.append(report)
    return reports
