"""Concurrency lint: inferred lock discipline over the control plane.

The master/agent/telemetry/serving control plane is the least-verified
code in the repo precisely because its bugs are not unit-testable: a
listener fired under the detector lock deadlocks only when the listener
re-enters, a gauge stored outside the lock loses only under a racing
rotation, a lock-order inversion hangs only when two threads interleave
just so. The review logs of PRs 6-15 show the same three bug classes
hand-found over and over. This pass makes them machine-checked:

  DLR009 blocking-call-under-lock   a held-lock region performs an
         unbounded wait: an RPC through a gRPC stub / ``MasterClient``,
         ``time.sleep``, ``Thread.join()`` without a timeout,
         ``queue.get/put`` without a timeout, ``jax.device_get`` /
         ``device_put`` (a device sync), or iterates a user-registered
         listener/callback/hook collection (the PR 7 deadlock class:
         an arbitrary callback runs with the lock held and may
         re-enter it).
  DLR010 mixed-guard-attribute      an instance attribute is written
         inside ``with self._lock:`` in one method but read or written
         lock-free in another: either the lock is not actually the
         guard (delete it) or the lock-free access is a race. Declared
         intent escapes the inference with a ``# guarded-by:``
         annotation on the attribute (see below).
  DLR011 lock-order-inversion       the whole-package lock-acquisition
         graph (lock A held while acquiring B => edge A->B, including
         acquisitions reached through method calls resolved one level
         deep) contains a cycle — two threads taking the same pair of
         locks in opposite orders deadlock; re-acquiring a non-reentrant
         ``threading.Lock`` you already hold (a self-edge) deadlocks a
         single thread.

The inference is deliberately syntactic, like ``ast_rules``: it
over-approximates in ways the checked-in ``baseline.json`` ratchet
absorbs (with per-entry rationale in the baseline's ``notes``) and
under-approximates in ways the fixtures in
``tests/test_concurrency_lint.py`` pin.

What counts as a lock
---------------------
An attribute (or module-level name) is treated as a lock when it is
assigned ``threading.Lock()`` / ``RLock()`` / ``Condition()`` /
``Semaphore()`` anywhere in the class/module, or when its name looks
lock-like (``_lock``, ``lock``, ``_mutex``, ``_cond`` ...) and it is
used as a context manager. A ``with`` on anything else (files, meshes,
trace scopes) is not a lock region.

Held-region inference
---------------------
A method body is ``with self._lock:``-held where the with-statement
says so. Additionally, a *helper* method that is only ever called from
held regions of its own class (the ``def _flag(self): ... # lock
held`` convention) is inferred held, to a fixpoint — so the classic
``observe() -> _judge() -> _flag()`` chain does not read as lock-free
access. A method called from both held and unheld sites stays unheld
(the unheld call path is real). Nested ``def``/``lambda`` bodies are
never held by the enclosing ``with`` (they run later, on whatever
thread calls them).

Annotations and suppressions
----------------------------
``# guarded-by: <lock>`` on a line mentioning ``self.<attr>`` declares
the attribute's guard discipline explicitly and exempts it from DLR010
inference (the declared intent is trusted; use it for
publish-once-then-read-only fields and single-writer counters).
``# dlrlint: disable=DLR0xx <reason>`` on the reported line suppresses
any DLR rule — the reason is MANDATORY; a bare disable is itself a
finding (DLR012) so suppressions cannot rot invisibly, and suppressed
counts surface in the CLI summary.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from dlrover_tpu.analysis.findings import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)

CONCURRENCY_RULES = ("DLR009", "DLR010", "DLR011")

# lock-like attribute/name spelling: the fallback when the assignment
# is not visible (injected locks, inherited attributes)
_LOCKY_NAME = re.compile(r"(?:^|_)(?:lock|locks|mutex|cond|condition)$")
# threading constructors that create a lock-like object, mapped to
# reentrancy: an RLock (and a Condition, which wraps an RLock by
# default) may be re-acquired by its holder; a plain Lock may not
_LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "rlock",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "JoinableQueue", "deque"}
# receivers whose method calls are RPC verbs (DLR009): the gRPC stub /
# MasterClient naming convention the whole control plane follows
_RPC_RECEIVER = re.compile(r"(?:client|stub)$", re.IGNORECASE)
# receiver names that look like bounded queues for .get/.put checks
_QUEUE_NAME = re.compile(r"(?:^|_)(?:queue|q)$")
# iterating one of these under a lock = firing arbitrary user callbacks
# with the lock held (the PR 7 verdict-listener deadlock class)
_CALLBACK_NAME = re.compile(r"(?:listener|callback|hook|subscriber)s?$")
_GUARDED_BY = re.compile(r"#\s*guarded-by:\s*(\S+)")

# methods whose lock-free attribute access is construction/teardown,
# not a race: the object is not yet (or no longer) shared
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _has_timeout(call: ast.Call) -> bool:
    # positional args count: `q.get(False)` is non-blocking and
    # `q.get(True, 5)` / `t.join(5)` carry the timeout positionally —
    # the caller has made a blocking decision either way
    return bool(call.args) or any(
        kw.arg in ("timeout", "block", None) for kw in call.keywords)


@dataclass
class _LockRef:
    """One acquisition target. ``key`` is the graph identity
    (``Class.attr`` / ``module.py:NAME``); '' = anonymous (a lock
    passed as an argument): the region still counts as held for
    DLR009/DLR010, but it cannot take part in the order graph."""

    key: str
    kind: str  # "lock" | "rlock" | "unknown"
    line: int


@dataclass
class _Site:
    line: int
    scope: str


@dataclass
class _MethodInfo:
    name: str
    scope: str  # Class.method (baseline scope key)
    # direct acquisitions anywhere in the body: (key, kind, line)
    acquires: List[Tuple[str, str, int]] = field(default_factory=list)
    # syntactically nested acquisitions: (held_key, acquired_key, line)
    nested: List[Tuple[str, str, int]] = field(default_factory=list)
    # blocking sites: (description, fixit, line, syntactically_held)
    blocking: List[Tuple[str, str, int, bool]] = field(
        default_factory=list)
    # self-attr accesses: (attr, is_write, line, syntactically_held)
    attr_access: List[Tuple[str, bool, int, bool]] = field(
        default_factory=list)
    # intra-class calls: (method_name, line, held_keys or None)
    self_calls: List[Tuple[str, int, Optional[Tuple[str, ...]]]] = field(
        default_factory=list)
    # calls through typed attributes: (attr, method, line, held_keys)
    attr_calls: List[
        Tuple[str, str, int, Optional[Tuple[str, ...]]]
    ] = field(default_factory=list)
    # non-reentrant self-acquire: (key, line) — an immediate deadlock
    self_deadlocks: List[Tuple[str, int]] = field(default_factory=list)


@dataclass
class _ClassInfo:
    name: str
    path: str
    bases: List[str] = field(default_factory=list)
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    queue_attrs: Set[str] = field(default_factory=set)
    guarded: Set[str] = field(default_factory=set)
    methods: Dict[str, _MethodInfo] = field(default_factory=dict)
    # filled by the held-method fixpoint: method -> held lock keys
    held_methods: Dict[str, Tuple[str, ...]] = field(default_factory=dict)


@dataclass
class FileSummary:
    """Everything the cross-file DLR011 pass needs from one file."""

    path: str
    classes: List[_ClassInfo] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)
    # inline-suppression table for anchoring DLR011 suppressions
    suppressions: Dict[int, Tuple[Set[str], str]] = field(
        default_factory=dict)


class _ClassScan(ast.NodeVisitor):
    """First pass over one class body: which attributes are locks,
    queues, or constructed from a known class (for one-level call
    resolution)."""

    def __init__(self, info: _ClassInfo):
        self.info = info
        # current method's annotated parameters: name -> bare type
        self._param_types: Dict[str, str] = {}

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._in_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._in_func(node)

    def _in_func(self, node):
        saved = self._param_types
        self._param_types = {}
        for arg in (node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs):
            if arg.annotation is not None:
                ann = _dotted(arg.annotation).rsplit(".", 1)[-1]
                if ann and ann[0].isupper():
                    self._param_types[arg.arg] = ann
        self.generic_visit(node)
        self._param_types = saved

    def visit_Assign(self, node: ast.Assign):
        self._record(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record([node.target], node.value)
        self.generic_visit(node)

    def _record(self, targets, value):
        ctor = ""
        if isinstance(value, ast.Call):
            ctor = _dotted(value.func).rsplit(".", 1)[-1]
        elif isinstance(value, ast.Name):
            # self._store = store, with `store: NodeRuntimeStore`
            # annotated on the enclosing signature
            ctor = self._param_types.get(value.id, "")
            if ctor:
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        self.info.attr_types.setdefault(tgt.attr, ctor)
            return
        if not ctor:
            return
        for tgt in targets:
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            if ctor in _LOCK_CTORS:
                self.info.lock_attrs[tgt.attr] = _LOCK_CTORS[ctor]
            elif ctor in _QUEUE_CTORS:
                self.info.queue_attrs.add(tgt.attr)
            elif ctor[0].isupper():
                self.info.attr_types[tgt.attr] = ctor


class _MethodScan(ast.NodeVisitor):
    """Per-method walk with a with-lock stack. Nested function/lambda
    bodies reset the stack (they execute later, unheld)."""

    def __init__(self, cls: _ClassInfo, method: _MethodInfo,
                 module_locks: Dict[str, str], path: str):
        self.cls = cls
        self.m = method
        self.module_locks = module_locks
        self.path = path
        self.held: List[_LockRef] = []

    # -- lock resolution -----------------------------------------------------

    def _resolve_lock(self, expr: ast.AST) -> Optional[_LockRef]:
        """A with-item's context expression -> lock ref, or None when
        it is not a lock (a file, a mesh, a span)."""
        line = getattr(expr, "lineno", 0)
        # unwrap `with self._lock as l:` handled by caller (item.context_expr)
        name = _dotted(expr)
        if not name:
            return None
        parts = name.split(".")
        last = parts[-1]
        if parts[0] == "self" and len(parts) == 2:
            kind = self.cls.lock_attrs.get(last)
            if kind is None and not _LOCKY_NAME.search(last):
                return None
            return _LockRef(f"{self.cls.name}.{last}", kind or "unknown",
                            line)
        if parts[0] == "self" and len(parts) == 3:
            # with self._store._lock: — resolve through the attr's type
            owner = self.cls.attr_types.get(parts[1])
            kind_known = owner is None  # kind resolved later, globally
            if not _LOCKY_NAME.search(last):
                return None
            if owner:
                return _LockRef(f"{owner}.{last}", "unknown", line)
            return _LockRef("", "unknown", line)
        if len(parts) == 1:
            kind = self.module_locks.get(last)
            if kind is not None:
                return _LockRef(f"{os.path.basename(self.path)}:{last}",
                                kind, line)
            if _LOCKY_NAME.search(last):
                # a lock passed as an argument / bound locally: held
                # region without a graph identity
                return _LockRef("", "unknown", line)
            return None
        # dotted module-level (Other._LOCK) or unknown receiver
        if _LOCKY_NAME.search(last):
            return _LockRef("", "unknown", line)
        return None

    # -- with statements -----------------------------------------------------

    def visit_With(self, node: ast.With):
        self._with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._with(node)

    def _with(self, node):
        entered = 0
        for item in node.items:
            ref = self._resolve_lock(item.context_expr)
            if ref is None:
                continue
            already = [h for h in self.held if h.key and h.key == ref.key]
            if already:
                # re-acquiring a held lock: reentrant (RLock/Condition)
                # is fine; a plain Lock deadlocks this very thread. An
                # unknown kind is assumed reentrant (no false alarm on
                # an injected lock we cannot see the constructor of).
                kind = ref.kind if ref.kind != "unknown" else \
                    already[0].kind
                if kind == "lock":
                    self.m.self_deadlocks.append((ref.key, ref.line))
                continue  # not a new node on the held stack
            if ref.key:
                self.m.acquires.append((ref.key, ref.kind, ref.line))
                for h in self.held:
                    if h.key and h.key != ref.key:
                        self.m.nested.append((h.key, ref.key, ref.line))
            self.held.append(ref)
            entered += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(entered):
            self.held.pop()

    # -- nested defs don't inherit the held stack ----------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._nested(node)

    def visit_Lambda(self, node: ast.Lambda):
        self._nested(node)

    def _nested(self, node):
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    # -- attribute accesses --------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr not in self.cls.lock_attrs):
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.m.attr_access.append(
                (node.attr, is_write, node.lineno, bool(self.held)))
        self.generic_visit(node)

    # -- blocking calls + call graph -----------------------------------------

    def _held_keys(self) -> Optional[Tuple[str, ...]]:
        if not self.held:
            return None
        return tuple(h.key for h in self.held if h.key)

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        parts = name.split(".") if name else []
        # record intra-class / typed-attr calls for one-level resolution
        if parts and parts[0] == "self":
            keys = self._held_keys()
            if len(parts) == 2:
                self.m.self_calls.append((parts[1], node.lineno, keys))
            elif len(parts) == 3 and parts[1] in self.cls.attr_types:
                self.m.attr_calls.append(
                    (parts[1], parts[2], node.lineno, keys))
        self._check_blocking(node, name, parts)
        self.generic_visit(node)

    def _blocked(self, node: ast.AST, desc: str, fixit: str):
        self.m.blocking.append(
            (desc, fixit, getattr(node, "lineno", 0), bool(self.held)))

    def _check_blocking(self, node: ast.Call, name: str,
                        parts: List[str]):
        last = parts[-1] if parts else (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else "")
        if not last:
            return
        if last == "sleep" and (len(parts) < 2 or parts[-2] in
                                ("time", "self")):
            self._blocked(
                node, "time.sleep() parks the thread with the lock "
                      "held — every peer path that needs the lock "
                      "stalls for the full sleep",
                "sleep outside the locked region (snapshot state under "
                "the lock, wait after releasing it)")
            return
        if (last == "join" and isinstance(node.func, ast.Attribute)
                and not node.args and not _has_timeout(node)
                and not isinstance(node.func.value, ast.Constant)):
            self._blocked(
                node, "Thread.join() without a timeout under a lock: "
                      "if the joined thread needs this lock to exit, "
                      "this is a deadlock, not a wait",
                "join outside the lock, or pass timeout= and handle "
                "the still-alive case")
            return
        if last in ("get", "put") and isinstance(node.func,
                                                 ast.Attribute):
            recv = ".".join(parts[:-1])
            recv_last = parts[-2] if len(parts) >= 2 else ""
            is_q = (_QUEUE_NAME.search(recv_last) is not None
                    or (recv.startswith("self.")
                        and recv_last in self.cls.queue_attrs))
            block_false = any(
                kw.arg == "block"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords)
            # queue.get(block, timeout): a positional 2nd arg is the
            # timeout; `get(True, 5)` is bounded
            positional_timeout = last == "get" and len(node.args) >= 2
            if is_q and not _has_timeout(node) and not block_false \
                    and not positional_timeout:
                self._blocked(
                    node, f"`{name}(...)` without a timeout under a "
                          f"lock blocks until a peer makes progress — "
                          f"and the peer may need this lock to do so",
                    "pass timeout= (handle Empty/Full), or move the "
                    "queue operation outside the locked region")
            return
        if last in ("device_get", "device_put", "block_until_ready"):
            self._blocked(
                node, f"`{name or last}(...)` under a lock blocks the "
                      f"holder on the device dispatch queue — host "
                      f"threads serialize behind a device sync",
                "materialize device values before taking the lock; "
                "hold the lock only for the host-state update")
            return
        if (len(parts) >= 2 and parts[-2] not in ("self",)
                and _RPC_RECEIVER.search(parts[-2])):
            self._blocked(
                node, f"RPC `{name}(...)` under a lock: the call "
                      f"blocks on a remote peer (dead peer = full "
                      f"rpc timeout) while every local path that "
                      f"needs the lock stalls behind it",
                "snapshot what the RPC needs under the lock, release, "
                "then call; re-take the lock to store the result")
            return
        if (len(parts) >= 3 and parts[0] == "self"
                and _RPC_RECEIVER.search(parts[1])):
            self._blocked(
                node, f"RPC `{name}(...)` under a lock: the call "
                      f"blocks on a remote peer (dead peer = full "
                      f"rpc timeout) while every local path that "
                      f"needs the lock stalls behind it",
                "snapshot what the RPC needs under the lock, release, "
                "then call; re-take the lock to store the result")

    # -- listener iteration under a lock -------------------------------------

    def visit_For(self, node: ast.For):
        tgt = node.iter
        # unwrap trivial copies: list(xs)/tuple(xs)/sorted(xs) — the
        # copy does not help if the loop STILL runs under the lock
        if (isinstance(tgt, ast.Call)
                and _dotted(tgt.func) in ("list", "tuple", "sorted")
                and tgt.args):
            tgt = tgt.args[0]
        name = _dotted(tgt)
        last = name.rsplit(".", 1)[-1] if name else ""
        if last and _CALLBACK_NAME.search(last):
            self.m.blocking.append((
                f"iterating `{name}` fires user-registered callbacks "
                f"with the lock held: a slow callback blocks every "
                f"peer, a re-entrant one deadlocks (the PR 7 "
                f"verdict-listener class)",
                "snapshot the collection under the lock and fire the "
                "callbacks after releasing it (the _drain_notices "
                "pattern)",
                node.lineno, bool(self.held)))
        self.generic_visit(node)


# -- per-file analysis -------------------------------------------------------


def _module_locks(tree: ast.Module) -> Dict[str, str]:
    locks: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            ctor = _dotted(node.value.func).rsplit(".", 1)[-1]
            if ctor in _LOCK_CTORS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        locks[tgt.id] = _LOCK_CTORS[ctor]
    return locks


def _guarded_attrs(source: str) -> Set[str]:
    """Attributes declared via ``# guarded-by:`` anywhere in the file.
    The annotation is per-line: every ``self.<attr>`` mentioned on a
    line carrying the annotation is declared."""
    out: Set[str] = set()
    for line in source.splitlines():
        if _GUARDED_BY.search(line):
            out.update(re.findall(r"self\.(\w+)", line))
    return out


def analyze_source(source: str, path: str) -> FileSummary:
    """Per-file pass: build the class/method tables and the DLR009
    findings (which need no cross-file knowledge). DLR010/DLR011 run
    in ``finalize`` once every file's summary exists (held-method
    inference wants the full class; the order graph wants the whole
    package)."""
    summary = FileSummary(path=path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return summary  # ast_rules already reports DLR000
    summary.suppressions = scan_suppressions(source)
    module_locks = _module_locks(tree)
    guarded = _guarded_attrs(source)

    def scan_class(node: ast.ClassDef, prefix: str = ""):
        info = _ClassInfo(name=prefix + node.name, path=path,
                          guarded=set(guarded))
        info.bases = [b for b in
                      (_dotted(x).rsplit(".", 1)[-1] for x in node.bases)
                      if b and b[0].isupper()]
        _ClassScan(info).visit(node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m = _MethodInfo(
                    name=stmt.name,
                    scope=f"{info.name}.{stmt.name}")
                ms = _MethodScan(info, m, module_locks, path)
                for sub in stmt.body:
                    ms.visit(sub)
                info.methods[stmt.name] = m
            elif isinstance(stmt, ast.ClassDef):
                scan_class(stmt, prefix=info.name + ".")
        summary.classes.append(info)

    # module-level functions get a pseudo-class so module locks still
    # produce held regions and graph edges
    pseudo = _ClassInfo(name=f"<{os.path.basename(path)}>", path=path,
                        guarded=set(guarded))
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            scan_class(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m = _MethodInfo(name=node.name, scope=node.name)
            ms = _MethodScan(pseudo, m, module_locks, path)
            for sub in node.body:
                ms.visit(sub)
            pseudo.methods[node.name] = m
    if pseudo.methods:
        summary.classes.append(pseudo)
    return summary


def _infer_held_methods(
    cls: _ClassInfo,
    extra_sites: Optional[
        Dict[str, List[Tuple[str, Optional[Tuple[str, ...]]]]]] = None,
) -> None:
    """Fixpoint: a method every one of whose call sites is held
    (syntactically, or inside an already-held method) — with at least
    one such site — is itself held, under the union of the callers'
    lock keys. Methods with an unheld call site, or never called
    intra-class (entry points), stay unheld. ``extra_sites`` carries
    call sites observed in SUBCLASSES (``self._helper()`` under the
    subclass's with-lock resolving to an inherited method): a held
    subclass site supports the inference, an unheld one vetoes it."""
    # collect intra-class call sites per callee
    sites: Dict[str, List[Tuple[str, Optional[Tuple[str, ...]]]]] = {}
    for m in cls.methods.values():
        for callee, _line, keys in m.self_calls:
            if callee in cls.methods:
                sites.setdefault(callee, []).append((m.name, keys))
    for callee, entries in (extra_sites or {}).items():
        sites.setdefault(callee, []).extend(entries)
    held: Dict[str, Tuple[str, ...]] = {}
    for _ in range(len(cls.methods) + 1):
        changed = False
        for name, callers in sites.items():
            if name in held:
                continue
            keys: Set[str] = set()
            ok = bool(callers)
            for caller, call_keys in callers:
                if call_keys is not None:
                    keys.update(call_keys)
                elif caller in held and caller != name:
                    keys.update(held[caller])
                else:
                    ok = False
                    break
            if ok:
                held[name] = tuple(sorted(keys))
                changed = True
        if not changed:
            break
    cls.held_methods = held


def _method_held(cls: _ClassInfo, m: _MethodInfo) -> bool:
    return m.name in cls.held_methods


def _emit_dlr009(cls: _ClassInfo, summary: FileSummary) -> None:
    for m in cls.methods.values():
        body_held = _method_held(cls, m)
        for desc, fixit, line, held in m.blocking:
            if not (held or body_held):
                continue
            via = "" if held else (
                " (lock held by every caller of this helper)")
            summary.findings.append(Finding(
                rule_id="DLR009", path=summary.path, line=line,
                message=desc + via, fixit=fixit, scope=m.scope))


def _emit_dlr010(cls: _ClassInfo, summary: FileSummary) -> None:
    # attr -> accesses folded over every method, with method-held
    # overlay applied
    locked_writes: Dict[str, List[Tuple[str, int]]] = {}
    unlocked: Dict[str, List[Tuple[str, bool, int]]] = {}
    for m in cls.methods.values():
        body_held = _method_held(cls, m)
        for attr, is_write, line, held in m.attr_access:
            if attr in cls.guarded:
                continue
            if held or body_held:
                if is_write:
                    locked_writes.setdefault(attr, []).append(
                        (m.name, line))
            elif m.name not in _EXEMPT_METHODS:
                unlocked.setdefault(attr, []).append(
                    (m.name, is_write, line))
    for attr, writes in sorted(locked_writes.items()):
        frees = unlocked.get(attr, [])
        write_methods = {m for m, _ in writes}
        # "written under a lock in one method, touched lock-free in
        # ANOTHER": a single method mixing with itself is not this rule
        offending = [(m, w, ln) for m, w, ln in frees
                     if any(m != mw for mw in write_methods)]
        if not offending:
            continue
        first = min(offending, key=lambda t: t[2])
        methods = sorted({m for m, _, _ in offending})
        kinds = "write" if any(w for _, w, _ in offending) else "read"
        summary.findings.append(Finding(
            rule_id="DLR010", path=summary.path, line=first[2],
            message=f"`self.{attr}` is written under the lock in "
                    f"`{sorted(write_methods)[0]}` but accessed "
                    f"lock-free ({kinds}) in "
                    f"{', '.join('`%s`' % m for m in methods[:3])}"
                    + (f" (+{len(methods) - 3} more)"
                       if len(methods) > 3 else "")
                    + ": either the lock is not the guard or the "
                      "lock-free access is a race",
            fixit="take the lock at the lock-free site, or declare "
                  "the discipline with a `# guarded-by: <lock>` "
                  "annotation where the attribute is initialized",
            scope=f"{cls.name}.{attr}"))


# -- the cross-file order graph (DLR011) -------------------------------------


@dataclass
class LockGraph:
    """Directed lock-acquisition graph with witness sites per edge."""

    edges: Dict[Tuple[str, str], List[_Site]] = field(
        default_factory=dict)
    kinds: Dict[str, str] = field(default_factory=dict)

    def add(self, a: str, b: str, path: str, line: int, scope: str):
        if a == b:
            return
        self.edges.setdefault((a, b), []).append(
            _Site(line=line, scope=f"{path}::{scope}"))

    def cycles(self) -> List[List[str]]:
        """Elementary cycles, smallest first — found via SCC then a
        bounded DFS inside each nontrivial component."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        sccs = _tarjan(adj)
        out: List[List[str]] = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            comp_set = set(comp)
            start = min(comp)
            cyc = _find_cycle(start, adj, comp_set)
            if cyc:
                out.append(cyc)
        return out


def _tarjan(adj: Dict[str, Set[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strong(v: str):
        # iterative Tarjan (control-plane files nest deep enough that
        # recursion limits are a real hazard in a lint pass)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strong(v)
    return sccs


def _find_cycle(start: str, adj: Dict[str, Set[str]],
                comp: Set[str]) -> List[str]:
    """One elementary cycle through ``start`` inside its SCC (BFS back
    to start gives a shortest one — the most readable witness)."""
    from collections import deque

    prev: Dict[str, str] = {}
    dq = deque([start])
    seen = {start}
    while dq:
        v = dq.popleft()
        for w in sorted(adj.get(v, ())):
            if w not in comp:
                continue
            if w == start:
                cyc = [v]
                while cyc[-1] != start:
                    cyc.append(prev[cyc[-1]])
                cyc.reverse()
                return cyc
            if w not in seen:
                seen.add(w)
                prev[w] = v
                dq.append(w)
    return []


def build_lock_graph(summaries: List[FileSummary]) -> LockGraph:
    graph = LockGraph()
    # global tables: lock kinds + class name -> info (ambiguous bare
    # names are dropped: a wrong resolution could fabricate a cycle)
    by_name: Dict[str, Optional[_ClassInfo]] = {}
    for s in summaries:
        for cls in s.classes:
            bare = cls.name.rsplit(".", 1)[-1]
            by_name[bare] = None if bare in by_name else cls
            for attr, kind in cls.lock_attrs.items():
                graph.kinds[f"{cls.name}.{attr}"] = kind
    # cross-hierarchy call sites, one level of inheritance each way:
    # up — `get_comm_world` holds the subclass lock and calls the
    # base's `_check_rdzv_completed`, so the base helper's guard
    # discipline is visible only through its subclasses; down — the
    # base's `join_rendezvous` calls `self._on_join()` under lock and
    # a subclass OVERRIDES the hook, so the override inherits the
    # base's (held) call sites
    inherited_sites: Dict[str, Dict[
        str, List[Tuple[_ClassInfo, str,
                        Optional[Tuple[str, ...]]]]]] = {}
    for s in summaries:
        for cls in s.classes:
            for base_name in cls.bases:
                base = by_name.get(base_name)
                if base is None:
                    continue
                for m in cls.methods.values():
                    for callee, _line, keys in m.self_calls:
                        if (callee in base.methods
                                and callee not in cls.methods):
                            inherited_sites.setdefault(
                                base.name, {}).setdefault(
                                callee, []).append((cls, m.name, keys))
                for bm in base.methods.values():
                    for callee, _line, keys in bm.self_calls:
                        if callee in cls.methods:
                            inherited_sites.setdefault(
                                cls.name, {}).setdefault(
                                callee, []).append(
                                (base, bm.name, keys))
    # two passes: an inherited call site inside a caller that is
    # ITSELF only inferred held (not syntactically) resolves against
    # the caller class's first-pass held map
    for _ in range(2):
        for s in summaries:
            for cls in s.classes:
                extra: Dict[str, List[
                    Tuple[str, Optional[Tuple[str, ...]]]]] = {}
                for callee, entries in inherited_sites.get(
                        cls.name, {}).items():
                    extra[callee] = [
                        (f"<{c.name}.{meth}>",
                         keys if keys is not None
                         else c.held_methods.get(meth))
                        for c, meth, keys in entries]
                _infer_held_methods(cls, extra)
    for s in summaries:
        for cls in s.classes:
            for m in cls.methods.values():
                # syntactic nesting
                for a, b, line in m.nested:
                    graph.add(a, b, s.path, line, m.scope)
                # a held helper's direct acquisitions nest under every
                # lock its callers hold
                held_keys = cls.held_methods.get(m.name, ())
                for key, _kind, line in m.acquires:
                    for h in held_keys:
                        graph.add(h, key, s.path, line, m.scope)
                # one-level call resolution: held call -> callee's
                # direct acquisitions
                for callee, line, keys in m.self_calls:
                    keys = keys if keys is not None else held_keys
                    target = cls.methods.get(callee)
                    if target is None:
                        for bname in cls.bases:
                            b = by_name.get(bname)
                            if b is not None and callee in b.methods:
                                target = b.methods[callee]
                                break
                    if not keys or target is None:
                        continue
                    for bkey, _k, _ln in target.acquires:
                        for h in keys:
                            graph.add(h, bkey, s.path, line, m.scope)
                for attr, meth, line, keys in m.attr_calls:
                    keys = keys if keys is not None else held_keys
                    if not keys:
                        continue
                    owner = by_name.get(cls.attr_types.get(attr, ""))
                    if owner is None:
                        continue
                    target = owner.methods.get(meth)
                    if target is None:
                        continue
                    for bkey, _k, _ln in target.acquires:
                        for h in keys:
                            graph.add(h, bkey, s.path, line, m.scope)
    return graph


def lock_order_findings(graph: LockGraph,
                        summaries: List[FileSummary]) -> List[Finding]:
    findings: List[Finding] = []
    for cyc in graph.cycles():
        # witness: the edge out of the smallest node (stable anchor)
        pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
        sites = graph.edges.get(pairs[0], [])
        anchor = sites[0] if sites else _Site(0, "")
        path, _, scope = anchor.scope.partition("::")
        order = " -> ".join(cyc + [cyc[0]])
        detail = "; ".join(
            f"{a}->{b} at "
            + (f"{graph.edges[(a, b)][0].scope.replace('::', ':')}"
               f":{graph.edges[(a, b)][0].line}"
               if graph.edges.get((a, b)) else "?")
            for a, b in pairs)
        findings.append(Finding(
            rule_id="DLR011", path=path or "<package>",
            line=anchor.line,
            message=f"lock-order inversion: {order} — two threads "
                    f"taking these locks in opposite orders deadlock "
                    f"[{detail}]",
            fixit="impose one global order (acquire the cycle's locks "
                  "in a fixed sequence everywhere), or restructure so "
                  "one side snapshots under its lock and calls out "
                  "lock-free",
            scope=scope.split("::")[-1] if scope else ""))
    # non-reentrant self-acquire: a cycle of length one
    for s in summaries:
        for cls in s.classes:
            for m in cls.methods.values():
                for key, line in m.self_deadlocks:
                    findings.append(Finding(
                        rule_id="DLR011", path=s.path, line=line,
                        message=f"`{key}` is a non-reentrant Lock "
                                f"re-acquired while already held: "
                                f"this thread deadlocks itself",
                        fixit="use threading.RLock, or split the "
                              "method so the locked region is entered "
                              "once",
                        scope=m.scope))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


# -- entry points ------------------------------------------------------------


def lint_paths_concurrency(
    paths: List[str], root: str,
    rules: Optional[Set[str]] = None,
    counters: Optional[Dict[str, int]] = None,
) -> List[Finding]:
    """Run DLR009/DLR010/DLR011 over every ``.py`` file under
    ``paths``. DLR011's graph spans exactly the files scanned — the
    full package in the default/tier-1 run; in ``--changed`` mode the
    graph (and so cycle detection) is limited to the changed files,
    which is the documented trade for the sub-second loop."""
    on = set(rules) if rules is not None else set(CONCURRENCY_RULES)
    if not on.intersection(CONCURRENCY_RULES):
        return []
    summaries: List[FileSummary] = []
    for path in paths:
        files: List[str] = []
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                files.extend(
                    os.path.join(dirpath, f) for f in sorted(filenames)
                    if f.endswith(".py"))
        elif path.endswith(".py"):
            files.append(path)
        for fname in files:
            with open(fname, encoding="utf-8") as fh:
                src = fh.read()
            rel = os.path.relpath(os.path.abspath(fname),
                                  os.path.abspath(root))
            summaries.append(analyze_source(src, rel.replace(os.sep,
                                                             "/")))
    graph = build_lock_graph(summaries)  # also runs held inference
    findings: List[Finding] = []
    for s in summaries:
        for cls in s.classes:
            if "DLR009" in on:
                _emit_dlr009(cls, s)
            if "DLR010" in on:
                _emit_dlr010(cls, s)
        findings.extend(s.findings)
    if "DLR011" in on:
        findings.extend(lock_order_findings(graph, summaries))
    # inline suppressions (per anchor file's table)
    by_path: Dict[str, Dict[int, Tuple[Set[str], str]]] = {
        s.path: s.suppressions for s in summaries}
    kept: List[Finding] = []
    for f in findings:
        table = by_path.get(f.path, {})
        out = apply_suppressions([f], table, counters=counters)
        kept.extend(out)
    kept.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return kept


def lint_source_concurrency(
    source: str, path: str,
    rules: Optional[Set[str]] = None,
    counters: Optional[Dict[str, int]] = None,
) -> List[Finding]:
    """Single-source convenience for fixtures: the per-file rules plus
    a lock graph built from this file alone."""
    on = set(rules) if rules is not None else set(CONCURRENCY_RULES)
    summary = analyze_source(source, path)
    graph = build_lock_graph([summary])
    findings: List[Finding] = []
    for cls in summary.classes:
        if "DLR009" in on:
            _emit_dlr009(cls, summary)
        if "DLR010" in on:
            _emit_dlr010(cls, summary)
    findings.extend(summary.findings)
    if "DLR011" in on:
        findings.extend(lock_order_findings(graph, [summary]))
    findings = apply_suppressions(findings, summary.suppressions,
                                  counters=counters)
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings
