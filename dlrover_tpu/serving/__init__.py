"""The elastic serving tier — continuous-batching decode on the same
runtime that trains (ROADMAP item 3).

The hard parts were already built for training and are REUSED, not
reimplemented: host-DRAM snapshot (``checkpoint.HostSnapshot``), GSPMD
resharding (``device_put`` against new shardings), the topology+knob
program cache with ``prewarm`` (``serving.engine`` mirrors
``trainer.elastic``), the master-side dispatch ledger generalized into
a request router (``serving.router`` <- PR 9's shard accounting), and
the runtime optimizer's live retune loop (serve knobs ride the same
``ParallelConfig`` broadcast).

Modules:
  kv_cache  paged, preallocated KV-cache pytree + its sharding rules
            (+ int8 page storage via ``ops.quantize``)
  engine    ServeEngine (compiled decode/prefill programs, program
            cache, prewarm, live resize, checkpoint->serving promotion)
            and ServeExecutor (continuous batching over fixed slots)
  router    RequestRouter on the master: enqueue/lease/complete over
            the existing ``comm`` surface, per-request latency
            accounting, re-lease of requests stranded on dead workers
  cli       ``tpurun serve`` / ``tpurun requests``
"""

from dlrover_tpu.serving.kv_cache import (  # noqa: F401
    KVCacheSpec,
    init_kv_cache,
    kv_cache_rules,
    resolve_kv_precision,
)


def __getattr__(name):
    # engine/router import jax-heavy modules; keep ``import
    # dlrover_tpu.serving`` light for CLI-only consumers
    if name in ("ServeEngine", "ServeExecutor", "ServeRequestState"):
        from dlrover_tpu.serving import engine

        return getattr(engine, name)
    if name == "RequestRouter":
        from dlrover_tpu.serving.router import RequestRouter

        return RequestRouter
    raise AttributeError(name)
