"""``tpurun serve`` / ``tpurun requests`` — the serving CLIs.

``tpurun serve --addr <master>`` runs one continuous-batching serve
worker (the demo tiny-llama model unless a driver script builds its
own ``ServeEngine``) against the master's request router, leasing
until the queue drains. ``tpurun requests`` renders the router ledger
— live (``--addr``) or forensically from the event timeline
(``--events``), the same two-view contract as ``tpurun data``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("serving.cli")


def _serve_main(argv: List[str]) -> int:
    p = argparse.ArgumentParser(
        prog="tpurun serve",
        description="run one continuous-batching serve worker")
    p.add_argument("--addr", required=True,
                   help="master address (host:port)")
    p.add_argument("--node_id", type=int, default=0)
    p.add_argument("--slots", type=int, default=None,
                   help="slot batch width (default: serve_slots knob)")
    p.add_argument("--prefill_chunk", type=int, default=None)
    p.add_argument("--kv_precision", default=None,
                   choices=["f32", "bf16", "int8"])
    p.add_argument("--max_seq", type=int, default=64,
                   help="KV pool depth per slot (tokens)")
    p.add_argument("--prefix_pool_pages", type=int, default=None,
                   help="shared prefix-cache pool width in pages "
                        "(0 disables; default: "
                        "serve_prefix_pool_pages knob)")
    p.add_argument("--spec_draft_len", type=int, default=None,
                   help="speculative-decode draft length K "
                        "(0 disables; default: "
                        "serve_spec_draft_len knob)")
    p.add_argument("--seed", type=int, default=0,
                   help="weight init seed of the demo model")
    args = p.parse_args(argv)

    import jax

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.serving.engine import ServeEngine, ServeExecutor

    cfg = llama.llama_tiny()
    params = llama.init(jax.random.PRNGKey(args.seed), cfg)
    engine = ServeEngine(
        cfg, strategy=Strategy(mesh=MeshPlan(data=-1),
                               rule_set="llama"),
        serve_slots=args.slots, prefill_chunk=args.prefill_chunk,
        kv_precision=args.kv_precision, max_seq=args.max_seq,
        prefix_pool_pages=args.prefix_pool_pages,
        spec_draft_len=args.spec_draft_len,
    )
    engine.prepare(params)
    client = MasterClient(args.addr, node_id=args.node_id)
    executor = ServeExecutor(engine, router_client=client)
    done = executor.serve()
    print(f"served {len(done)} requests "
          f"({executor.decode_steps} decode steps)")
    client.close()
    return 0


def _forensic_report(events_path: str) -> dict:
    from dlrover_tpu.telemetry.events import read_events
    from dlrover_tpu.telemetry.names import EventKind

    records = read_events(events_path)
    resizes = [r for r in records
               if r.get("kind") == EventKind.SERVE_RESIZE_DONE]

    def count(kind):
        return sum(1 for r in records if r.get("kind") == kind)

    return {
        # the live-vs-forensic agreement contract (the `tpurun data`
        # gate pattern): these counts must match get_serve_report()'s
        # ledger after any run whose full timeline is on file
        "requests": {
            "submitted": count(EventKind.SERVE_REQUEST_SUBMITTED),
            # the ROUTER's accepted completions (worker-side DONE
            # events double on a re-leased twin; the router dedups)
            "completed": count(EventKind.SERVE_REQUEST_COMPLETED),
            "evicted": count(EventKind.SERVE_REQUEST_EVICTED),
            "leases_expired": count(EventKind.SERVE_LEASE_EXPIRED),
        },
        "runs": count(EventKind.SERVE_START),
        "completed_runs": [
            {"decode_steps": r.get("decode_steps"),
             "completed": r.get("completed")}
            for r in records if r.get("kind") == EventKind.SERVE_END
        ],
        "resizes": [
            {"world_from": r.get("world_from"),
             "world_to": r.get("world_to"),
             "seconds": r.get("reshard_seconds"),
             "recompiled": r.get("recompiled")}
            for r in resizes
        ],
        "evicted": count(EventKind.SERVE_REQUEST_EVICTED),
        "leases_expired": count(EventKind.SERVE_LEASE_EXPIRED),
        # the prefix-cache columns: worker-side HIT edges carry the
        # admitted token count; EVICTED edges carry evicted page counts
        "prefix": {
            "hits": count(EventKind.SERVE_PREFIX_HIT),
            "saved_prefill_tokens": sum(
                int(r.get("hit_tokens", 0) or 0) for r in records
                if r.get("kind") == EventKind.SERVE_PREFIX_HIT),
            "evicted_pages": sum(
                int(r.get("pages", 0) or 0) for r in records
                if r.get("kind") == EventKind.SERVE_PREFIX_EVICTED),
        },
        # the speculative-decode columns ride the router's accepted
        # COMPLETED edges (worker DONE twins would double-count), so
        # the sums here must equal the live spec_summary()'s totals
        # and wasted stays derived, never separately accumulated
        "spec": _spec_forensic(records),
    }


def _spec_forensic(records) -> dict:
    from dlrover_tpu.telemetry.names import EventKind

    drafted = accepted = 0
    for r in records:
        if r.get("kind") != EventKind.SERVE_REQUEST_COMPLETED:
            continue
        drafted += int(r.get("spec_drafted") or 0)
        accepted += int(r.get("spec_accepted") or 0)
    return {
        "drafted_tokens": drafted,
        "accepted_tokens": accepted,
        "wasted_tokens": drafted - accepted,
        "accept_rate": (round(accepted / drafted, 4)
                        if drafted else -1.0),
    }


def _requests_main(argv: List[str]) -> int:
    p = argparse.ArgumentParser(
        prog="tpurun requests",
        description="the request-router ledger (live or forensic)")
    p.add_argument("--addr", default="",
                   help="live view: master address")
    p.add_argument("--events", default="",
                   help="forensic view: event-timeline JSONL path")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    if not args.addr and not args.events:
        print("tpurun requests: need --addr or --events",
              file=sys.stderr)
        return 2
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        report = client.get_serve_report()
        client.close()
    else:
        report = _forensic_report(args.events)
    if args.as_json:
        print(json.dumps(report, indent=2))
        return 0
    if args.addr:
        r = report.get("requests", {})
        print("requests: submitted=%s completed=%s queued=%s "
              "leased=%s dropped=%s leases_expired=%s" % (
                  r.get("submitted"), r.get("completed"),
                  r.get("queued"), r.get("leased"), r.get("dropped"),
                  r.get("leases_expired")))
        lat = report.get("latency", {})
        print("latency: ttft p50=%s p95=%s  e2e p50=%s p95=%s (s)" % (
            lat.get("ttft_p50_s"), lat.get("ttft_p95_s"),
            lat.get("e2e_p50_s"), lat.get("e2e_p95_s")))
        pref = report.get("prefix") or {}
        if pref:
            print("prefix: hits=%s saved_tokens=%s hit_rate=%s "
                  "affinity_routed=%s" % (
                      pref.get("hits"),
                      pref.get("saved_prefill_tokens"),
                      pref.get("hit_rate"),
                      pref.get("affinity_routed")))
        for node, row in sorted(report.get("nodes", {}).items(),
                                key=lambda kv: int(kv[0])):
            print(f"  node {node}: leased={row.get('leased')} "
                  f"done={row.get('done')} tokens={row.get('tokens')}")
    else:
        print(json.dumps(report, indent=2))
    return 0


def _slo_main(argv: List[str]) -> int:
    """``tpurun serve slo`` — the serving SLO plane: live (``--addr``:
    declared targets, burn rates, active verdicts, scale proposals)
    or forensic (``--events``: the slot-seconds ledger derived from
    SERVE_END records plus the violation/recovery trail)."""
    p = argparse.ArgumentParser(
        prog="tpurun serve slo",
        description="serving SLO verdicts + the slot-time ledger")
    p.add_argument("--addr", default="",
                   help="live view: master address")
    p.add_argument("--events", default="",
                   help="forensic view: event-timeline JSONL path")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    if not args.addr and not args.events:
        print("tpurun serve slo: need --addr or --events",
              file=sys.stderr)
        return 2
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        report = client.get_serve_slo()
        client.close()
    else:
        from dlrover_tpu.telemetry.events import read_events
        from dlrover_tpu.telemetry.goodput import derive_slot_ledger
        from dlrover_tpu.telemetry.names import EventKind

        records = read_events(args.events)
        report = {
            "ledger": derive_slot_ledger(records),
            "violations": [
                {"slo": r.get("slo"), "observed": r.get("observed"),
                 "target": r.get("target"),
                 "burn_rate": r.get("burn_rate"),
                 "trace_id": r.get("trace_id")}
                for r in records
                if r.get("kind") == EventKind.SERVE_SLO_VIOLATION
            ],
            "recovered": [
                {"slo": r.get("slo"),
                 "violated_seconds": r.get("violated_seconds"),
                 "trace_id": r.get("trace_id")}
                for r in records
                if r.get("kind") == EventKind.SERVE_SLO_RECOVERED
            ],
            "scale_proposals": [
                {"direction": r.get("direction"),
                 "reason": r.get("reason"),
                 "trace_id": r.get("trace_id")}
                for r in records
                if r.get("kind") == EventKind.SERVE_SCALE_PROPOSED
            ],
        }
    if args.as_json:
        print(json.dumps(report, indent=2))
        return 0
    if args.addr:
        print("targets: %s (window %ss, confirm %s)" % (
            report.get("targets"), report.get("window_secs"),
            report.get("confirm_windows")))
        verdicts = report.get("verdicts", {})
        if not verdicts:
            print("verdicts: none active")
        for slo, v in verdicts.items():
            print(f"  VIOLATION {slo}: {v.get('evidence')} "
                  f"[{v.get('trace_id')}]")
        for prop in report.get("proposals", []):
            print(f"  proposal: {prop.get('direction')} "
                  f"({prop.get('reason')}) [{prop.get('trace_id')}]")
        pref = report.get("prefix") or {}
        if pref:
            print("prefix: hits=%s saved_tokens=%s hit_rate=%s "
                  "affinity_routed=%s" % (
                      pref.get("hits"),
                      pref.get("saved_prefill_tokens"),
                      pref.get("hit_rate"),
                      pref.get("affinity_routed")))
    else:
        ledger = report.get("ledger", {})
        print("slot-seconds ledger (%s runs, %.3f slot-s, coverage "
              "%s):" % (ledger.get("runs"),
                        ledger.get("slot_seconds") or 0.0,
                        ledger.get("coverage")))
        for cls, row in ledger.get("buckets", {}).items():
            print(f"  {cls:>14}: {row['seconds']:>10.3f}s "
                  f"({row['fraction'] * 100:.1f}%)")
        pref = ledger.get("prefix") or {}
        if pref:
            print("  prefix: hits=%s misses=%s evictions=%s "
                  "saved_tokens=%s" % (
                      pref.get("hits"), pref.get("misses"),
                      pref.get("evictions"),
                      pref.get("saved_prefill_tokens")))
        for v in report.get("violations", []):
            print(f"  VIOLATION {v['slo']}: observed={v['observed']} "
                  f"target={v['target']} burn={v['burn_rate']} "
                  f"[{v['trace_id']}]")
        for r in report.get("recovered", []):
            print(f"  recovered {r['slo']} after "
                  f"{r['violated_seconds']}s [{r['trace_id']}]")
        for prop in report.get("scale_proposals", []):
            print(f"  proposal: {prop['direction']} ({prop['reason']})")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: tpurun serve|requests ...", file=sys.stderr)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "serve":
        if rest and rest[0] == "slo":
            return _slo_main(rest[1:])
        return _serve_main(rest)
    if cmd == "requests":
        return _requests_main(rest)
    print(f"unknown serving command {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
