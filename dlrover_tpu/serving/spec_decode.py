"""Host-side n-gram / prompt-lookup draft proposer for speculative
decode.

No second model, no extra HBM: drafts come from the request's OWN
token stream (prompt + already-emitted tokens). ``NgramProposer``
keeps a per-request suffix index — every n-gram that has occurred maps
to where its continuation starts — and proposes the continuation of
the most recent earlier occurrence of the current suffix, longest
n-gram first. The workload this wins on is repetitive / structured
text (templated output, code, retrieval-stuffed prompts): exactly
where prompt-lookup decoding is known to hit.

Correctness never depends on draft quality: drafts feed
``models.llama.verify_step``, whose greedy acceptance emits bitwise
what plain greedy decode would at every acceptance pattern — a bad
draft only wastes the verify step's extra positions. The proposer is
therefore free to be heuristic and the engine is free to inject a
different one (tests force 0%/100%/alternating patterns through the
``ServeExecutor.spec_proposer`` hook).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

MAX_NGRAM_DEFAULT = 4
MIN_NGRAM_DEFAULT = 1


class NgramProposer:
    """Per-request incremental suffix index + prompt-lookup drafting.

    ``propose(history, k)`` self-syncs from the canonical host history
    (which only ever grows by appends: the prompt is fixed and decode
    appends), so callers never have to hook token-append sites. Index
    update is O(max_ngram) per new token; lookup is O(max_ngram) per
    proposal. For each n-gram key the index keeps the last TWO
    continuation starts: the most recent registration of the current
    suffix is the suffix itself (its "continuation" is the future —
    the thing being predicted), so lookups fall back to the previous
    occurrence.
    """

    def __init__(self, max_ngram: int = MAX_NGRAM_DEFAULT,
                 min_ngram: int = MIN_NGRAM_DEFAULT):
        self.max_ngram = max(1, int(max_ngram))
        self.min_ngram = max(1, min(int(min_ngram), self.max_ngram))
        self._history: List[int] = []
        # n-gram -> (last continuation start, previous one or None)
        self._index: Dict[Tuple[int, ...],
                          Tuple[int, Optional[int]]] = {}

    def _sync(self, history: Sequence[int]) -> None:
        h = self._history
        for i in range(len(h), len(history)):
            h.append(int(history[i]))
            for n in range(1, self.max_ngram + 1):
                if n > i + 1:
                    break
                key = tuple(h[i - n + 1:i + 1])
                prev = self._index.get(key)
                self._index[key] = (i + 1,
                                    prev[0] if prev else None)

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        """Up to ``k`` draft tokens continuing ``history``; [] when no
        earlier occurrence of any suffix n-gram exists."""
        self._sync(history)
        if k <= 0:
            return []
        h = self._history
        length = len(h)
        for n in range(min(self.max_ngram, length),
                       self.min_ngram - 1, -1):
            key = tuple(h[length - n:])
            entry = self._index.get(key)
            if entry is None:
                continue
            last, prev = entry
            start = last if last < length else prev
            if start is None or start >= length:
                continue
            # The match says position ``start`` aligns with position
            # ``length``: the stream looks like it repeats with period
            # d = length - start. Extend the draft by that period when
            # the literal continuation runs off the end of history —
            # without this, a period-d loop near the tail (d < k) can
            # never draft more than d tokens per step.
            d = length - start
            return [h[start + (j % d)] for j in range(k)]
        return []
