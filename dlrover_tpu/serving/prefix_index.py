"""Host-side radix index over page-grain token chunks for the shared
prefix pool.

The device pool (``serving.kv_cache.init_prefix_pool``) is a flat array
of ``num_pages`` KV pages; THIS structure decides what each page means.
It is a trie whose edges are exact ``page_size``-token tuples — a match
walks child dictionaries keyed by the literal token ids, so a hit IS an
exact token comparison and a hash collision is impossible by
construction (there is no hash shortcut to collide; dict key equality
compares the full tuple).

Refcounts pin pages for the admit window of a live request: a pinned
node (or any ancestor of one — children imply their parents) is never
an eviction victim. Eviction is LRU over refcount-0 LEAF nodes only, so
the invariant "every indexed page's whole prefix chain is present"
holds at all times; evicting a node removes it from the trie, which is
what makes page-id reuse safe — a stale page can never be matched
again, the next request with that prefix simply misses and prefills.

``release`` is idempotent per handle and survives ``flush`` (the handle
keeps references to the orphaned node objects, so decrementing them
after a flush touches nothing reachable) — refcounts can never dangle
across a pool rebuild or a live resize.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import get_logger

logger = get_logger("serving.prefix_index")


@dataclass
class _Node:
    chunk: Tuple[int, ...]
    page_id: int
    parent: Optional["_Node"]
    refcount: int = 0
    last_use: int = 0
    children: Dict[Tuple[int, ...], "_Node"] = field(default_factory=dict)


@dataclass
class PrefixHandle:
    """A pin over one matched chain; ``release`` through the index is
    idempotent (the handle remembers it was released)."""

    nodes: List[_Node]
    released: bool = False

    @property
    def pages(self) -> List[int]:
        return [n.page_id for n in self.nodes]

    @property
    def tokens(self) -> int:
        return sum(len(n.chunk) for n in self.nodes)


class PrefixIndex:
    """Refcounted radix index mapping token-chunk chains to pool pages."""

    def __init__(self, page_size: int, num_pages: int):
        if page_size <= 0:
            raise ValueError("page_size must be positive")
        self.page_size = int(page_size)
        self.capacity = max(0, int(num_pages))
        self._root: Dict[Tuple[int, ...], _Node] = {}
        self._by_page: Dict[int, _Node] = {}
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._clock = itertools.count(1)
        # cumulative stats (survive flush — they describe the process,
        # not the current pool contents)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.published = 0
        self.publish_skipped = 0
        self.saved_tokens = 0

    # -- introspection -------------------------------------------------------

    @property
    def used_pages(self) -> int:
        return len(self._by_page)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def stats(self) -> Dict[str, int]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "published": self.published,
            "publish_skipped": self.publish_skipped,
            "saved_tokens": self.saved_tokens,
            "used_pages": self.used_pages,
            "capacity": self.capacity,
        }

    # -- match / pin ---------------------------------------------------------

    def _walk(self, tokens: Sequence[int]) -> List[_Node]:
        pg = self.page_size
        out: List[_Node] = []
        level = self._root
        for i in range(0, len(tokens) - pg + 1, pg):
            chunk = tuple(int(t) for t in tokens[i:i + pg])
            node = level.get(chunk)
            if node is None:
                break
            out.append(node)
            level = node.children
        return out

    def match(self, tokens: Sequence[int],
              max_pages: Optional[int] = None,
              align_pages: int = 1) -> Optional[PrefixHandle]:
        """Longest exact chain of full pages matching the leading
        tokens, pinned. Returns None on a zero-page match (and counts a
        miss). ``max_pages`` caps the chain (the engine's strictly-
        below-prompt-length cap); ``align_pages`` rounds it DOWN to a
        whole multiple (the engine's lcm(page, chunk) bitwise grain) —
        both applied BEFORE pinning, so only used pages are pinned."""
        chain = self._walk(tokens)
        if max_pages is not None:
            chain = chain[:max(0, int(max_pages))]
        a = max(1, int(align_pages))
        chain = chain[:(len(chain) // a) * a]
        if not chain:
            self.misses += 1
            return None
        now = next(self._clock)
        for node in chain:
            node.refcount += 1
            node.last_use = now
        self.hits += 1
        self.saved_tokens += len(chain) * self.page_size
        return PrefixHandle(nodes=chain)

    def release(self, handle: Optional[PrefixHandle]) -> None:
        """Idempotent unpin; safe on handles that predate a flush (the
        orphaned nodes absorb the decrement harmlessly)."""
        if handle is None or handle.released:
            return
        handle.released = True
        for node in handle.nodes:
            node.refcount = max(0, node.refcount - 1)

    # -- publish -------------------------------------------------------------

    def _evictable(self) -> List[_Node]:
        return [n for n in self._by_page.values()
                if n.refcount == 0 and not n.children]

    def _evict_one(self) -> Optional[int]:
        victims = self._evictable()
        if not victims:
            return None
        victim = min(victims, key=lambda n: n.last_use)
        level = (victim.parent.children if victim.parent is not None
                 else self._root)
        level.pop(victim.chunk, None)
        del self._by_page[victim.page_id]
        self.evictions += 1
        return victim.page_id

    def reserve_page(self) -> Optional[int]:
        """A free page id, LRU-evicting an unpinned leaf when the pool
        is full. None when every page is pinned or an ancestor of a
        pinned/live chain — the caller degrades to miss-and-prefill."""
        if self._free:
            return self._free.pop()
        return self._evict_one()

    def publish(self, tokens: Sequence[int]) -> List[Tuple[int, int]]:
        """Index the full pages of ``tokens`` that are not yet present.
        Returns ``[(page_index_within_prompt, pool_page_id), ...]`` for
        the NEWLY indexed pages — the caller must copy each slot page
        into its pool page. A full pool (all pages pinned) skips the
        remainder: logged and counted, never raised."""
        pg = self.page_size
        out: List[Tuple[int, int]] = []
        if self.capacity == 0:
            return out
        level = self._root
        parent: Optional[_Node] = None
        now = next(self._clock)
        for idx, i in enumerate(range(0, len(tokens) - pg + 1, pg)):
            chunk = tuple(int(t) for t in tokens[i:i + pg])
            node = level.get(chunk)
            if node is None:
                page_id = self.reserve_page()
                if page_id is None:
                    self.publish_skipped += 1
                    logger.debug(
                        "prefix pool full (all pages pinned); skipping "
                        "publish of %d remaining pages",
                        (len(tokens) - i) // pg)
                    break
                node = _Node(chunk=chunk, page_id=page_id, parent=parent,
                             last_use=now)
                level[chunk] = node
                self._by_page[page_id] = node
                self.published += 1
                out.append((idx, page_id))
            else:
                node.last_use = now
            parent = node
            level = node.children
        return out

    # -- lifecycle -----------------------------------------------------------

    def flush(self) -> None:
        """Drop every indexed page (pool rebuild / prefill-chunk grain
        change). Outstanding handles keep their orphaned node objects,
        so a later ``release`` is a no-op — no refcount can dangle into
        the fresh index."""
        self._root = {}
        self._by_page = {}
        self._free = list(range(self.capacity - 1, -1, -1))
