"""Worker-side serving SLO plane: the node-report hook.

``ServeRuntimeReportHook`` is the serving twin of the trainer's
``NodeRuntimeReportHook`` (PR 6): it pushes node-tagged snapshots of
the serve worker's instruments — cumulative decode-step histogram
bucket counts, tokens/decode-step totals, slot occupancy, local queue
depth — through the SAME ``comm.NodeRuntimeReport`` path, with
``node_type="serve"``. The master's node-series store diffs them into
windowed per-node samples, exports ``{node=}``-labeled serving gauges
on ``/metrics``, and the straggler detector judges slow DECODE workers
against their serve peers exactly as it judges training stragglers
(evidence carries ``workload: serve``).

Discipline carried over verbatim from the training hook: the decode
loop only snapshots and enqueues; the RPC and the ``/proc`` RSS read
run on a background daemon sender thread; backpressure drops the
report (the next cadence supersedes it); and the send rate is floored
by wall time so a fast decode loop cannot flood the master.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import get_registry, names as tm
from dlrover_tpu.telemetry.metrics import LATENCY_BUCKETS

logger = get_logger("serving.slo")


class ServeRuntimeReportHook:
    """Push serve-worker runtime snapshots to the master every
    ``every_steps`` decode steps, wall-time-floored by
    ``min_interval_s`` (default: the master's
    ``seconds_interval_to_report``)."""

    def __init__(self, master_client, every_steps: Optional[int] = None,
                 registry=None, min_interval_s: Optional[float] = None):
        import queue

        ctx = get_context()
        self._client = master_client
        self._every = int(
            every_steps if every_steps is not None
            else getattr(ctx, "runtime_report_steps", 32))
        self._min_interval = float(
            min_interval_s if min_interval_s is not None
            else getattr(ctx, "seconds_interval_to_report", 15))
        self._last_send = 0.0
        # 0.0, not a -1 sentinel: a run with ZERO decode steps must
        # also skip the flush (an all-zero report is exactly the
        # empty-window sample the flush guard exists to avoid)
        self._last_steps_sent = 0.0
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._sender: Optional[threading.Thread] = None
        reg = registry if registry is not None else get_registry()
        self._reg = reg
        self._h_step = reg.histogram(
            tm.SERVE_STEP_TIME, buckets=LATENCY_BUCKETS)
        self._c_decode = reg.counter(tm.SERVE_DECODE_STEPS)
        self._c_tokens = reg.counter(tm.SERVE_TOKENS)
        self._g_occupancy = reg.gauge(tm.SERVE_SLOT_OCCUPANCY)
        self._c_spec_drafted = reg.counter(tm.SERVE_SPEC_DRAFTED)
        self._c_spec_accepted = reg.counter(tm.SERVE_SPEC_ACCEPTED)
        self._c_sent = get_registry().counter(
            tm.NODE_RUNTIME_REPORTS,
            help="node runtime snapshots pushed to the master")
        self._c_failed = get_registry().counter(
            tm.NODE_RUNTIME_REPORT_FAILURES,
            help="runtime snapshots the master never acked")

    def after_step(self, step: int, queue_len: int = 0,
                   slots: int = 0) -> None:
        """Called by the executor after each decode step; snapshots
        and enqueues at the configured cadence."""
        if self._every <= 0 or step % self._every:
            return
        now = time.monotonic()
        if now - self._last_send < self._min_interval:
            return
        self._last_send = now
        self._enqueue(step, queue_len, slots)

    def flush(self, queue_len: int = 0, slots: int = 0) -> None:
        """One final snapshot regardless of cadence (SERVE_END) — but
        ONLY when steps landed since the last send: a zero-window
        report would become the node's latest sample with p50=None,
        and a peer whose latest window is empty can no longer anchor
        the straggler median. Then stop the sender after the queue
        drains (bounded join — exit must not hang on a dead master)."""
        if self._every > 0 and \
                float(self._c_decode.value) != self._last_steps_sent:
            self._enqueue(int(self._c_decode.value), queue_len, slots)
        if self._sender is None or not self._sender.is_alive():
            return
        try:
            self._queue.put_nowait(None)
        except Exception:  # noqa: BLE001 — full queue: sender is wedged
            logger.debug("serve report queue full at flush",
                         exc_info=True)
            return
        self._sender.join(timeout=5.0)
        self._sender = None

    def _enqueue(self, step: int, queue_len: int, slots: int) -> None:
        import queue

        bounds = getattr(self._h_step, "bounds", None)  # null when off
        counts = self._h_step.snapshot_counts()
        self._last_steps_sent = float(self._c_decode.value)
        payload = dict(
            node_type="serve",
            step=int(step),
            steps_total=float(self._c_decode.value),
            bounds=list(bounds) if bounds else None,
            step_time_counts=list(counts) if counts else None,
            serve_tokens_total=float(self._c_tokens.value),
            serve_queue_len=float(queue_len),
            serve_slot_occupancy=float(self._g_occupancy.value),
            serve_slots=float(slots),
            # cumulative spec totals: the master diffs consecutive
            # reports into a WINDOWED acceptance rate, so a regression
            # shows up immediately instead of being averaged away by
            # the worker's lifetime totals
            serve_spec_drafted_total=float(
                self._c_spec_drafted.value),
            serve_spec_accepted_total=float(
                self._c_spec_accepted.value),
        )
        if self._sender is None or not self._sender.is_alive():
            self._sender = threading.Thread(
                target=self._send_loop, name="serve-runtime-report",
                daemon=True,
            )
            self._sender.start()
        try:
            self._queue.put_nowait(payload)
        except queue.Full:
            # sender is behind (slow/dead master): drop — the next
            # cadence's cumulative snapshot supersedes this one
            self._c_failed.inc()

    def _rss_mb(self) -> float:
        try:
            import psutil

            return psutil.Process().memory_info().rss / (1024 * 1024)
        except Exception:  # noqa: BLE001 — psutil-less hosts
            logger.debug("psutil rss read failed; using getrusage",
                         exc_info=True)
            import resource

            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0

    def _send_loop(self):
        while True:
            payload = self._queue.get()
            if payload is None:
                return
            try:
                payload["rss_mb"] = round(self._rss_mb(), 1)
                self._client.report_node_runtime(**payload)
                self._c_sent.inc()
            except Exception:  # noqa: BLE001 — a dead master must not
                # kill the decode loop; the gap is counted
                self._c_failed.inc()
                logger.debug("serve runtime report failed",
                             exc_info=True)
