"""The request router — the master's serving control plane.

The PR 9 ``BatchDatasetManager`` dispatch ledger, generalized from
shards to requests: enqueue (todo) → lease (doing) → complete (done),
with the same invariants re-pointed at serving:

  * a leased request belongs to exactly one worker until it completes
    or its lease EXPIRES (the shard-timeout machinery: a request
    stranded on a dead/wedged worker re-queues to a live one — counted
    and evented, because the re-lease re-decodes the prompt);
  * accounting is conservation-checked: every submitted request is
    queued, leased, or done at all times — ``dropped_total`` counts
    conservation violations and the resize wedge pins it at ZERO;
  * per-request latency lands in master-side histograms (TTFT,
    per-token, end-to-end), the serving twin of the shard
    dispatch→complete latency histogram.

Leases survive a live resize by construction: the worker process never
dies (PR 5 in-process reshard), so its leases simply keep ticking —
the router HOLDS them, and only the expiry scan (a genuinely dead
worker) ever takes a request back.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    SpanName,
    emit_event,
    get_registry,
    names as tm,
    span,
)
from dlrover_tpu.telemetry.metrics import COUNT_BUCKETS, LATENCY_BUCKETS
from dlrover_tpu.telemetry.trace_context import new_trace_id

logger = get_logger("serving.router")

_id_seq = itertools.count()


def new_request_trace_id() -> str:
    """A per-request trace id, minted at submission: every lifecycle
    event of the request (router AND worker pids) carries it, so
    ``tpurun trace --events`` stitches one lane per request."""
    return "req-" + new_trace_id()[len("inc-"):]


@dataclass
class ServeRequest:
    request_id: str
    prompt: List[int]
    max_new_tokens: int
    eos_id: int = -1
    state: str = "queued"  # queued | leased | done
    node_id: int = -1
    trace_id: str = ""
    enqueue_ts: float = 0.0
    lease_ts: float = 0.0
    # when an expiry re-queued the request: queue-wait of the NEXT
    # lease is measured from here, not from the original enqueue
    requeue_ts: float = 0.0
    first_lease_ts: float = 0.0
    done_ts: float = 0.0
    releases: int = 0
    tokens: List[int] = field(default_factory=list)
    ttft_s: Optional[float] = None
    e2e_s: Optional[float] = None
    error_code: str = ""
    # speculative-decode ledger columns, worker-reported at
    # completion: drafted - accepted = wasted per request
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0

    def wire(self) -> Dict[str, Any]:
        return {
            "request_id": self.request_id,
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "eos_id": self.eos_id,
            "trace_id": self.trace_id,
        }


class RequestRouter:
    def __init__(self, lease_timeout_secs: Optional[float] = None):
        from dlrover_tpu.common.config import get_context

        self._lock = threading.Lock()
        self._timeout = float(
            lease_timeout_secs if lease_timeout_secs is not None
            else getattr(get_context(), "serve_lease_timeout_secs", 120.0))
        self._queue: "deque[ServeRequest]" = deque()
        self._requests: Dict[str, ServeRequest] = {}
        self._node_touch: Dict[int, float] = {}
        # instance-local totals: the registry counters below are
        # process-wide (shared across router instances in tests); the
        # ledger must report THIS router's ledger
        self._n_submitted = 0
        self._n_completed = 0
        self._n_dropped = 0
        self._n_expired = 0
        # completions that carried the eviction error code (the
        # worker could not fit the request): counted so the live
        # ledger and the forensic --events view agree on all four of
        # submitted/completed/evicted/expired
        self._n_evicted = 0
        # bounded done-ledger: a long-lived serving master must not
        # retain every completed request's prompt+tokens forever (the
        # decision-trail deque precedent) — completion order, oldest
        # pruned past the cap. Totals above keep counting; only the
        # per-request records age out.
        self._done_order: "deque[str]" = deque()
        self._done_retention_cap = 4096
        # incremental state counts, updated at every transition: the
        # gauges/ledger must not rescan every tracked request under
        # the lock on the serving hot path
        self._live_counts = {"queued": 0, "leased": 0, "done": 0}
        # prefix-hit ledger (worker-reported at completion) + the soft
        # session-affinity map: prefix key -> the node whose pool
        # first served it. SOFT: correctness never depends on routing
        # (a worker without the pages exact-misses and prefills), so
        # pass 2 of lease() fills spare capacity FIFO from anywhere —
        # affinity can never starve a request.
        self._n_prefix_hits = 0
        self._n_prefix_hit_tokens = 0
        self._n_affinity_routed = 0
        # speculative-decode ledger (worker-reported at completion):
        # drafted = accepted + wasted — wasted is DERIVED, never
        # accumulated separately, so the conservation identity holds
        # by construction at the job grain and the per-request columns
        # must sum to it (what the conservation test pins)
        self._n_spec_drafted = 0
        self._n_spec_accepted = 0
        self._prefix_home: Dict[tuple, int] = {}
        self._prefix_home_cap = 4096
        self._affinity = bool(getattr(
            get_context(), "serve_prefix_affinity", True))
        reg = get_registry()
        self._c_submitted = reg.counter(
            tm.SERVE_REQUESTS_SUBMITTED,
            help="requests enqueued on the router")
        self._c_completed = reg.counter(
            tm.SERVE_REQUESTS_COMPLETED,
            help="requests completed by workers")
        self._c_dropped = reg.counter(
            tm.SERVE_REQUESTS_DROPPED,
            help="requests lost without completion or re-lease "
                 "(conservation violations — must stay 0)")
        self._c_expired = reg.counter(
            tm.SERVE_LEASES_EXPIRED,
            help="leases expired on a silent worker and re-queued")
        self._g_queued = reg.gauge(
            tm.SERVE_REQUESTS_QUEUED, help="requests waiting for a lease")
        self._g_leased = reg.gauge(
            tm.SERVE_REQUESTS_LEASED, help="requests leased to workers")
        self._h_ttft = reg.histogram(
            tm.SERVE_TTFT_TIME, buckets=LATENCY_BUCKETS,
            help="admit -> first token wall seconds")
        self._h_e2e = reg.histogram(
            tm.SERVE_E2E_TIME, buckets=LATENCY_BUCKETS,
            help="admit -> completion wall seconds")
        self._h_queue_wait = reg.histogram(
            tm.SERVE_QUEUE_WAIT_TIME, buckets=LATENCY_BUCKETS,
            help="enqueue (or re-queue) -> lease wall seconds")
        self._h_tpot = reg.histogram(
            tm.SERVE_TPOT_TIME, buckets=LATENCY_BUCKETS,
            help="inter-token seconds: (e2e - ttft) / (tokens - 1)")
        self._h_tokens = reg.histogram(
            tm.SERVE_TOKENS_PER_REQUEST, buckets=COUNT_BUCKETS,
            help="tokens generated per completed request")
        self._c_affinity = reg.counter(
            tm.SERVE_PREFIX_AFFINITY_ROUTED,
            help="requests leased to the node already homing their "
                 "prefix pages")

    # -- the three verbs -----------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int,
               request_id: str = "", eos_id: int = -1) -> str:
        with self._lock:
            rid = request_id or f"req-{next(_id_seq)}"
            if rid in self._requests:
                # idempotent re-submit (a retried RPC): keep the first
                return rid
            req = ServeRequest(
                request_id=rid, prompt=[int(t) for t in prompt],
                max_new_tokens=int(max_new_tokens), eos_id=int(eos_id),
                trace_id=new_request_trace_id(),
                enqueue_ts=time.time(),
            )
            self._requests[rid] = req
            self._queue.append(req)
            self._live_counts["queued"] += 1
            self._n_submitted += 1
            self._c_submitted.inc()
            self._refresh_gauges()
            emit_event(
                EventKind.SERVE_REQUEST_SUBMITTED,
                trace_id=req.trace_id, request_id=rid,
                prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens,
            )
            return rid

    # prefix-key grain for session affinity: enough leading tokens to
    # separate system prompts, few enough that a shared header still
    # collides into ONE home. Routing is advisory — the worker's radix
    # index does the exact-token comparison that decides a hit.
    _PREFIX_KEY_TOKENS = 16

    @classmethod
    def _prefix_key(cls, prompt: List[int]) -> tuple:
        return tuple(int(t) for t in prompt[:cls._PREFIX_KEY_TOKENS])

    def _select_for_lease(self, node_id: int,
                          want: int) -> List[ServeRequest]:
        """Pop up to ``want`` queued requests for ``node_id``. Pass 1
        (affinity on): FIFO over requests homed on this node or not
        yet homed (claiming a home as it goes); pass 2 fills any spare
        capacity FIFO regardless of home, so affinity skews placement
        but can never starve the queue or idle a worker."""
        want = max(0, int(want))
        if not self._affinity:
            out = []
            while self._queue and len(out) < want:
                out.append(self._queue.popleft())
            return out
        selected: List[ServeRequest] = []
        rest: List[ServeRequest] = []
        for req in self._queue:
            if len(selected) < want:
                key = self._prefix_key(req.prompt)
                home = self._prefix_home.get(key)
                if home is None or home == int(node_id):
                    if home == int(node_id):
                        self._n_affinity_routed += 1
                        self._c_affinity.inc()
                    self._prefix_home[key] = int(node_id)
                    while len(self._prefix_home) > self._prefix_home_cap:
                        self._prefix_home.pop(
                            next(iter(self._prefix_home)))
                    selected.append(req)
                    continue
            rest.append(req)
        while rest and len(selected) < want:
            # spare capacity: take foreign-homed work FIFO (the home
            # map is NOT rewritten — a capacity steal must not flap
            # the affinity of a busy prefix)
            selected.append(rest.pop(0))
        self._queue = deque(rest)
        return selected

    def lease(self, node_id: int, max_requests: int) -> List[Dict]:
        self.scan_expired_once()
        out = []
        leased_meta = []
        with self._lock, span(SpanName.SERVE_LEASE, node=int(node_id)):
            now = time.time()
            self._node_touch[int(node_id)] = now
            for req in self._select_for_lease(node_id, max_requests):
                req.state = "leased"
                self._live_counts["queued"] -= 1
                self._live_counts["leased"] += 1
                req.node_id = int(node_id)
                req.lease_ts = now
                if not req.first_lease_ts:
                    req.first_lease_ts = now
                wait = max(0.0, now - (req.requeue_ts
                                       or req.enqueue_ts))
                self._h_queue_wait.observe(wait)
                leased_meta.append((req.trace_id, req.request_id,
                                    req.releases, wait))
                out.append(req.wire())
            if out:
                self._refresh_gauges()
        for tid, rid, releases, wait in leased_meta:
            emit_event(
                EventKind.SERVE_REQUEST_LEASED,
                trace_id=tid, request_id=rid, lease_node=int(node_id),
                queue_wait_s=round(wait, 6),
                releases=releases,
            )
        return out

    def complete(self, node_id: int, request_id: str,
                 tokens: List[int], ttft_s: Optional[float] = None,
                 e2e_s: Optional[float] = None,
                 error_code: str = "",
                 prefix_hit_tokens: int = 0,
                 spec_drafted_tokens: int = 0,
                 spec_accepted_tokens: int = 0) -> bool:
        with self._lock, span(SpanName.SERVE_COMPLETE,
                              node=int(node_id)):
            self._node_touch[int(node_id)] = time.time()
            req = self._requests.get(request_id)
            if req is None or req.state == "done":
                return False  # a re-leased twin already completed it
            if req.state == "queued":
                # completed by the ORIGINAL worker after an expiry
                # re-queued it: accept the result and pull it back out
                # of the queue (no duplicate decode)
                try:
                    self._queue.remove(req)
                except ValueError:
                    pass
                self._live_counts["queued"] -= 1
            else:
                self._live_counts["leased"] -= 1
            req.state = "done"
            self._live_counts["done"] += 1
            req.done_ts = time.time()
            req.tokens = [int(t) for t in tokens or []]
            req.ttft_s, req.e2e_s = ttft_s, e2e_s
            req.error_code = error_code or ""
            self._n_completed += 1
            if error_code == "SERVE_REQUEST_EVICTED":
                self._n_evicted += 1
            if prefix_hit_tokens and int(prefix_hit_tokens) > 0:
                self._n_prefix_hits += 1
                self._n_prefix_hit_tokens += int(prefix_hit_tokens)
            # spec columns accumulate INSIDE the done-guard, like the
            # counters above: a re-leased twin's duplicate completion
            # (the guard's False branch) must not double-charge the
            # ledger, and a worker whose verify step failed reported
            # ZERO drafted for those steps — its draft credit was
            # restored at the source, so conservation holds here too
            drafted = max(0, int(spec_drafted_tokens or 0))
            accepted = min(max(0, int(spec_accepted_tokens or 0)),
                           drafted)
            req.spec_drafted_tokens = drafted
            req.spec_accepted_tokens = accepted
            self._n_spec_drafted += drafted
            self._n_spec_accepted += accepted
            self._done_order.append(req.request_id)
            while len(self._done_order) > self._done_retention_cap:
                if self._requests.pop(self._done_order.popleft(),
                                      None) is not None:
                    self._live_counts["done"] -= 1
            self._c_completed.inc()
            tpot = None
            if ttft_s is not None:
                self._h_ttft.observe(float(ttft_s))
            if e2e_s is not None:
                self._h_e2e.observe(float(e2e_s))
                if ttft_s is not None and len(req.tokens) > 1:
                    # the decode-phase inter-token latency: the TTFT
                    # (queue + prefill + first token) is subtracted so
                    # TPOT judges ONLY the steady decode stream
                    tpot = max(0.0, (float(e2e_s) - float(ttft_s))
                               / (len(req.tokens) - 1))
                    self._h_tpot.observe(tpot)
            self._h_tokens.observe(float(len(req.tokens)))
            self._refresh_gauges()
            emit_event(
                EventKind.SERVE_REQUEST_COMPLETED,
                trace_id=req.trace_id, request_id=request_id,
                complete_node=int(node_id), tokens=len(req.tokens),
                ttft_s=ttft_s, e2e_s=e2e_s,
                tpot_s=round(tpot, 6) if tpot is not None else None,
                completed_error_code=error_code or None,
                spec_drafted=drafted or None,
                spec_accepted=accepted if drafted else None,
            )
            return True

    def touch(self, node_id: int):
        with self._lock:
            self._node_touch[int(node_id)] = time.time()

    # -- expiry (the shard-timeout machinery, re-pointed) --------------------

    def scan_expired_once(self, timeout_secs: Optional[float] = None
                          ) -> List[str]:
        """Re-queue leased requests whose worker has been silent past
        the lease timeout — the dead-worker re-lease path. The request
        re-decodes from its prompt on the next worker (counted and
        evented: duplicate work, never a drop)."""
        timeout = float(timeout_secs if timeout_secs is not None
                        else self._timeout)
        if timeout <= 0:
            return []
        requeued: List[str] = []
        with self._lock:
            now = time.time()
            for req in self._requests.values():
                if req.state != "leased":
                    continue
                last = max(req.lease_ts,
                           self._node_touch.get(req.node_id, 0.0))
                if now - last <= timeout:
                    continue
                req.state = "queued"
                self._live_counts["leased"] -= 1
                self._live_counts["queued"] += 1
                req.releases += 1
                req.requeue_ts = now
                stranded_node = req.node_id
                req.node_id = -1
                self._queue.append(req)
                requeued.append(req.request_id)
                self._n_expired += 1
                self._c_expired.inc()
                emit_event(
                    EventKind.SERVE_LEASE_EXPIRED,
                    error_code="SERVE_LEASE_EXPIRED",
                    trace_id=req.trace_id,
                    request_id=req.request_id,
                    stranded_node=stranded_node,
                    lease_age_s=round(now - last, 1),
                )
            if requeued:
                self._refresh_gauges()
                logger.warning("re-leased %d stranded requests: %s",
                               len(requeued), requeued[:8])
        return requeued

    # -- accounting ----------------------------------------------------------

    def _counts(self) -> Dict[str, int]:
        return dict(self._live_counts)

    def _refresh_gauges(self):
        c = self._counts()
        self._g_queued.set(c["queued"])
        self._g_leased.set(c["leased"])
        # conservation: every submitted request is in exactly one
        # state. TODAY this cannot fire (the three states are
        # exhaustive by construction) — it guards FUTURE code paths
        # that remove entries; the PRIMARY zero-drop check is the
        # completed-equals-submitted arithmetic the resize wedge and
        # the bench resize leg pin, plus `oldest_lease_age_s` in the
        # report for leases a live-but-stuck worker never completes.
        lost = len(self._requests) - sum(c.values())
        if lost > 0:
            self._n_dropped += lost
            self._c_dropped.inc(lost)
            logger.error("request conservation violated: %d lost", lost)

    def dropped(self) -> int:
        with self._lock:
            return self._n_dropped

    def queue_depth(self) -> int:
        with self._lock:
            return self._live_counts["queued"]

    def slo_observations(self) -> Dict[str, Any]:
        """The SLO engine's per-evaluation snapshot: current queue
        depth plus the CUMULATIVE TTFT histogram counts (the engine
        diffs consecutive snapshots into rolling-window percentiles —
        the node-series discipline)."""
        with self._lock:
            counts = self._h_ttft.snapshot_counts()
            return {
                "queue_depth": self._live_counts["queued"],
                "leased": self._live_counts["leased"],
                "ttft_bounds": list(getattr(self._h_ttft, "bounds",
                                            ()) or ()),
                "ttft_counts": (list(counts)
                                if counts is not None else None),
            }

    def report(self) -> Dict[str, Any]:
        """The ``tpurun requests`` ledger."""
        from dlrover_tpu.telemetry.metrics import percentile_from_counts

        with self._lock:
            counts = self._counts()
            per_node: Dict[int, Dict[str, int]] = {}
            for r in self._requests.values():
                if r.node_id < 0:
                    continue
                row = per_node.setdefault(
                    r.node_id, {"leased": 0, "done": 0, "tokens": 0})
                if r.state == "leased":
                    row["leased"] += 1
                elif r.state == "done":
                    row["done"] += 1
                    row["tokens"] += len(r.tokens)

            def pct(h, q):
                b = getattr(h, "bounds", None)
                cts = h.snapshot_counts()
                if not b or cts is None:
                    return None
                return percentile_from_counts(b, cts, q)

            now = time.time()
            oldest_lease = max(
                (now - r.first_lease_ts
                 for r in self._requests.values()
                 if r.state == "leased" and r.first_lease_ts), default=0.0)
            return {
                "requests": {
                    **counts,
                    "submitted": self._n_submitted,
                    "completed": self._n_completed,
                    "dropped": self._n_dropped,
                    "leases_expired": self._n_expired,
                    "evicted": self._n_evicted,
                    # a live-but-stuck worker keeps touching, so its
                    # lease never expires: the age of the OLDEST open
                    # lease is the operator's visibility into that
                    # failure mode (expiry only catches SILENT workers)
                    "oldest_lease_age_s": round(oldest_lease, 1),
                },
                "latency": {
                    "ttft_p50_s": pct(self._h_ttft, 0.50),
                    "ttft_p95_s": pct(self._h_ttft, 0.95),
                    "e2e_p50_s": pct(self._h_e2e, 0.50),
                    "e2e_p95_s": pct(self._h_e2e, 0.95),
                    "queue_wait_p50_s": pct(self._h_queue_wait, 0.50),
                    "queue_wait_p95_s": pct(self._h_queue_wait, 0.95),
                    "tpot_p50_s": pct(self._h_tpot, 0.50),
                    "tpot_p95_s": pct(self._h_tpot, 0.95),
                },
                "nodes": {str(n): v
                          for n, v in sorted(per_node.items())},
                "prefix": self._prefix_summary_locked(),
                "spec": self._spec_summary_locked(),
            }

    def _prefix_summary_locked(self) -> Dict[str, Any]:
        done = max(0, self._n_completed - self._n_evicted)
        return {
            "hits": self._n_prefix_hits,
            "saved_prefill_tokens": self._n_prefix_hit_tokens,
            "hit_rate": (round(self._n_prefix_hits / done, 4)
                         if done else 0.0),
            "affinity_routed": self._n_affinity_routed,
        }

    def prefix_summary(self) -> Dict[str, Any]:
        """The prefix-hit ledger alone (the ``serve slo`` view rides
        it next to the SLO verdicts)."""
        with self._lock:
            return self._prefix_summary_locked()

    def _spec_summary_locked(self) -> Dict[str, Any]:
        drafted = self._n_spec_drafted
        accepted = self._n_spec_accepted
        return {
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            # derived, so drafted = accepted + wasted by construction
            # at the job grain; the retained per-request columns must
            # sum to these totals (the conservation test's check)
            "wasted_tokens": drafted - accepted,
            "accept_rate": (round(accepted / drafted, 4)
                            if drafted else -1.0),
        }

    def spec_summary(self) -> Dict[str, Any]:
        """The speculative-decode ledger alone."""
        with self._lock:
            return self._spec_summary_locked()
