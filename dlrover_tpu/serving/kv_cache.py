"""Paged, preallocated KV cache for the serving tier.

Geometry: one pool of ``num_slots * pages_per_slot`` pages per layer,
``page_size`` tokens each, laid out SLOT-MAJOR — slot ``s`` owns the
contiguous pages ``[s * pages_per_slot, (s+1) * pages_per_slot)``, so a
leaf is shaped ``[L, S, T, KV, HD]`` with ``T = pages_per_slot *
page_size``. Slot-major contiguity is what makes the decode read
GATHER-FREE: attention for slot ``s`` is a plain slice of its own rows
(no page-table indirection on the hot path), while admission/eviction
still swap page *ranges* with ``lax.dynamic_update_slice``-style index
ops — fixed shapes, zero recompiles as the active set churns.

Sharding: KV heads shard on the "tensor" axis (the same axis the
attention projections are Megatron-split on, so the per-head pages live
where the heads compute) and the slot dimension shards on the
``(data, fsdp)`` axes (each data shard serves its own slots) — or
replicates when the slot count does not divide them, the same graceful
degradation every rule in ``parallel.sharding_rules`` has. The rules
regex-COMPOSE with the existing training rule sets (the
``wire_residual`` precedent from PR 12): one ``ShardingRules`` object
shards ``{"params": ..., "cache": ...}`` with the params falling
through to the unchanged training rules, which is what makes
checkpoint->serving promotion a pure ``device_put``.

Storage precision (``serve_kv_precision`` knob): "f32"/"bf16" pages
store the compute dtype; "int8" stores int8 values + f32 per-block
scales (``ops.quantize.quantize_block_scaled_int8``), ~1/4 of an f32
page — decode is KV-READ memory-bound, so smaller pages are capacity
AND step-time. The G109 "kv" drift family ratchets the numerics.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.ops.quantize import (
    KV_PRECISIONS,
    dequantize_block_scaled_int8,
    quantize_block_scaled_int8,
    resolve_quant_block,
)
from dlrover_tpu.parallel.sharding_rules import ShardingRules

logger = get_logger("serving.kv_cache")

_INT8_KV_SUPPORTED: Optional[bool] = None


def int8_kv_supported() -> bool:
    """Capability probe for int8 KV storage (the ``fp8_wire_supported``
    pattern): a tiny round-trip must execute on the default backend.
    Probed once per process; a failing backend degrades the knob to
    "f32" — logged, never raised."""
    global _INT8_KV_SUPPORTED
    if _INT8_KV_SUPPORTED is not None:
        return _INT8_KV_SUPPORTED
    try:
        import numpy as np

        with jax.ensure_compile_time_eval():
            x = jnp.asarray(np.asarray([[1.0, -2.0, 0.5, 0.25]],
                                       np.float32))
            v, s = quantize_block_scaled_int8(x, block=4)
            back = dequantize_block_scaled_int8(v, s)
            jax.block_until_ready(back)
            _INT8_KV_SUPPORTED = bool(
                np.allclose(np.asarray(back), np.asarray(x), atol=0.02))
    except Exception:  # noqa: BLE001 — a probe failure means "no"
        logger.warning("int8 KV probe failed", exc_info=True)
        _INT8_KV_SUPPORTED = False
    return _INT8_KV_SUPPORTED


def resolve_kv_precision(requested: Optional[str] = None) -> str:
    """The effective KV-page storage precision: an explicit request
    wins, else the Context knob (``serve_kv_precision``). "int8"
    degrades to "f32" when the backend fails the probe."""
    from dlrover_tpu.common.config import get_context

    p = (requested or "").strip()
    if not p:
        p = str(getattr(get_context(), "serve_kv_precision", "f32")
                or "f32").strip() or "f32"
    if p not in KV_PRECISIONS:
        raise ValueError(
            f"unknown KV-cache precision {p!r}; choose one of "
            f"{KV_PRECISIONS}"
        )
    if p == "int8" and not int8_kv_supported():
        logger.warning(
            "serve_kv_precision=int8 requested but the backend fails "
            "the int8 probe; KV pages stay f32")
        return "f32"
    return p


@dataclass(frozen=True)
class KVCacheSpec:
    """Static geometry of one serving world's KV pool."""

    num_layers: int
    num_kv_heads: int
    head_dim: int
    num_slots: int
    page_size: int = 16
    pages_per_slot: int = 8
    # "f32" | "bf16" | "int8" (see resolve_kv_precision)
    precision: str = "f32"
    # shared prefix pool, in pages (0 = off): a device-resident pool of
    # refcounted KV pages BESIDE the slot pool, indexed host-side by
    # serving.prefix_index — admission copies matched pages into the
    # slot's contiguous range (copy-on-admit), so the decode read stays
    # gather-free and the slot programs never see the pool
    prefix_pool_pages: int = 0

    @property
    def max_seq(self) -> int:
        return self.page_size * self.pages_per_slot

    @property
    def quant_block(self) -> int:
        return resolve_quant_block(self.head_dim)

    @property
    def scale_blocks(self) -> int:
        return self.head_dim // self.quant_block

    def bytes_per_slot(self) -> int:
        """Residency of ONE slot's K+V pages, priced by the planner's
        ``kv_bytes_per_elem`` — the ONE formula the decode term, the
        HBM feasibility gate and this spec share."""
        from dlrover_tpu.parallel.planner import kv_bytes_per_elem

        elems = (self.num_layers * self.max_seq
                 * self.num_kv_heads * self.head_dim)
        return int(2 * elems  # K and V
                   * kv_bytes_per_elem(self.precision, self.head_dim))

    def prefix_page_bytes(self) -> int:
        """Residency of ONE prefix-pool page (K+V for every layer),
        priced by the SAME ``kv_bytes_per_elem`` formula the slot pool,
        the HBM feasibility gate and the planner's decode term share."""
        from dlrover_tpu.parallel.planner import kv_bytes_per_elem

        elems = (self.num_layers * self.page_size
                 * self.num_kv_heads * self.head_dim)
        return int(2 * elems  # K and V
                   * kv_bytes_per_elem(self.precision, self.head_dim))

    def prefix_pool_bytes(self) -> int:
        return self.prefix_page_bytes() * self.prefix_pool_pages

    def total_bytes(self) -> int:
        return (self.bytes_per_slot() * self.num_slots
                + self.prefix_pool_bytes())

    @classmethod
    def from_model(cls, config, num_slots: int, max_seq: int = 0,
                   page_size: int = 16,
                   precision: Optional[str] = None,
                   prefix_pool_pages: int = 0) -> "KVCacheSpec":
        """Derive the pool geometry from a model config (LlamaConfig-
        shaped). ``max_seq`` rounds UP to a whole number of pages."""
        want = int(max_seq or config.max_seq_len)
        pages = max(1, math.ceil(want / page_size))
        return cls(
            num_layers=int(config.num_layers),
            num_kv_heads=int(config.num_kv_heads),
            head_dim=int(config.head_dim),
            num_slots=int(num_slots),
            page_size=int(page_size),
            pages_per_slot=pages,
            precision=resolve_kv_precision(precision),
            prefix_pool_pages=max(0, int(prefix_pool_pages)),
        )

    def with_slots(self, num_slots: int) -> "KVCacheSpec":
        return replace(self, num_slots=int(num_slots))


def store_dtype(spec: KVCacheSpec):
    if spec.precision == "int8":
        return jnp.int8
    if spec.precision == "bf16":
        return jnp.bfloat16
    return jnp.float32


def init_kv_cache(spec: KVCacheSpec) -> Dict[str, Any]:
    """The preallocated pool pytree. Leaves:

      k, v           [L, S, T, KV, HD]   page payload (store dtype)
      k_scale, v_scale [L, S, T, KV, NB] f32 per-block scales (int8 only)
      length         [S] int32           tokens written per slot

    Zero-filled: position ``t`` is never READ before it is written
    (decode masks ``t <= pos`` and writes position ``pos`` first), so
    stale pages need no invalidation pass on slot reuse.
    """
    l, s = spec.num_layers, spec.num_slots
    t, kv, hd = spec.max_seq, spec.num_kv_heads, spec.head_dim
    cache: Dict[str, Any] = {
        "k": jnp.zeros((l, s, t, kv, hd), store_dtype(spec)),
        "v": jnp.zeros((l, s, t, kv, hd), store_dtype(spec)),
        "length": jnp.zeros((s,), jnp.int32),
    }
    if spec.precision == "int8":
        nb = spec.scale_blocks
        cache["k_scale"] = jnp.ones((l, s, t, kv, nb), jnp.float32)
        cache["v_scale"] = jnp.ones((l, s, t, kv, nb), jnp.float32)
    return cache


def init_prefix_pool(spec: KVCacheSpec) -> Dict[str, Any]:
    """The shared prefix pool pytree — a flat array of
    ``prefix_pool_pages`` KV pages (K+V for every layer per page; the
    host-side ``PrefixIndex`` decides what each page means). Leaves:

      k, v             [L, P, page_size, KV, HD]  (store dtype)
      k_scale, v_scale [L, P, page_size, KV, NB]  f32 (int8 only)

    Zero-filled; a page is never matched before it is published, so
    stale bytes need no invalidation pass on page-id reuse (the index
    removes an evicted node from the trie FIRST)."""
    l, p = spec.num_layers, spec.prefix_pool_pages
    pg, kv, hd = spec.page_size, spec.num_kv_heads, spec.head_dim
    pool: Dict[str, Any] = {
        "k": jnp.zeros((l, p, pg, kv, hd), store_dtype(spec)),
        "v": jnp.zeros((l, p, pg, kv, hd), store_dtype(spec)),
    }
    if spec.precision == "int8":
        nb = spec.scale_blocks
        pool["k_scale"] = jnp.ones((l, p, pg, kv, nb), jnp.float32)
        pool["v_scale"] = jnp.ones((l, p, pg, kv, nb), jnp.float32)
    return pool


# -- page copies between the prefix pool and the slot pool --------------------
#
# Copy-on-admit: a hit COPIES the matched pool pages into the slot's
# contiguous page range, so the decode read stays a plain slice of the
# slot's own rows (gather-free) and the decode/prefill programs never
# change shape — zero recompiles, one compiled copy program for every
# hit length (H pages = H calls of the same program with traced
# indices; every window is page-aligned and inside the pool, so the
# ``dynamic_update_slice`` clamp hazard cannot bite).


def copy_page_to_slot(cache: Dict[str, Any], pool: Dict[str, Any],
                      slot, dst_start, src_page,
                      spec: KVCacheSpec) -> Dict[str, Any]:
    """One pool page -> the slot rows ``[dst_start, dst_start+page)``.
    Pure; jitted by the engine with the cache donated."""
    import jax.lax as lax

    out = dict(cache)
    for name in pool:
        leaf = pool[name]
        l, _, pg, kvh, last = leaf.shape
        page = lax.dynamic_slice(
            leaf, (0, src_page, 0, 0, 0), (l, 1, pg, kvh, last))
        out[name] = lax.dynamic_update_slice(
            cache[name], page, (0, slot, dst_start, 0, 0))
    return out


def copy_page_to_pool(pool: Dict[str, Any], cache: Dict[str, Any],
                      slot, src_start, dst_page,
                      spec: KVCacheSpec) -> Dict[str, Any]:
    """The slot rows ``[src_start, src_start+page)`` -> one pool page
    (publish after a completed prefill). Pure; pool donated."""
    import jax.lax as lax

    out = dict(pool)
    for name in pool:
        leaf = cache[name]
        l, _, _, kvh, last = leaf.shape
        pg = spec.page_size
        page = lax.dynamic_slice(
            leaf, (0, slot, src_start, 0, 0), (l, 1, pg, kvh, last))
        out[name] = lax.dynamic_update_slice(
            pool[name], page, (0, dst_page, 0, 0, 0))
    return out


# -- encode/decode at the page boundary --------------------------------------


def encode_kv(x: jax.Array, spec: KVCacheSpec):
    """Token K/V (``[..., KV, HD]`` compute dtype) -> (payload, scales-
    or-None) in the page storage format."""
    if spec.precision == "int8":
        v, s = quantize_block_scaled_int8(
            x.astype(jnp.float32), block=spec.quant_block)
        return v, s
    return x.astype(store_dtype(spec)), None


def decode_kv(values: jax.Array, scales: Optional[jax.Array],
              spec: KVCacheSpec, dtype=jnp.float32) -> jax.Array:
    """Page storage -> compute dtype (the read side of the pool)."""
    if spec.precision == "int8":
        return dequantize_block_scaled_int8(values, scales, dtype)
    return values.astype(dtype)


# -- sharding ----------------------------------------------------------------


def kv_cache_rules(base_rule_set: str = "llama") -> ShardingRules:
    """The serving rule set: KV-pool rules prepended to the UNCHANGED
    training rules of ``base_rule_set`` (regex-compose, first match
    wins — the ``moe_ep_rules`` / ``wire_residual`` pattern), so one
    rule object shards ``{"params": ..., "cache": ...}`` and the params
    land exactly where training would put them."""
    from dlrover_tpu.parallel.strategy import RULE_SETS

    factory = RULE_SETS.get(base_rule_set)
    if factory is None:
        raise ValueError(
            f"unknown base rule set {base_rule_set!r}; "
            f"have {sorted(RULE_SETS)}"
        )
    base = factory()
    return ShardingRules(rules=[
        # pool payload [L, S, T, KV, HD]: heads on the model axis,
        # slots data-sharded (each data shard serves its own slots)
        (r"cache/(k|v)$", (None, ("data", "fsdp"), None, "tensor", None)),
        # int8 scale side-band [L, S, T, KV, NB] rides with its payload
        (r"cache/(k|v)_scale$",
         (None, ("data", "fsdp"), None, "tensor", None)),
        (r"cache/length$", (("data", "fsdp"),)),
        # prefix pool [L, P, page, KV, HD]: heads follow the slot pool
        # onto the model axis; the PAGE dimension replicates — any data
        # shard's slot may admit any page, and replication is what
        # makes the per-device HBM charge the conservative, undivided
        # pool_bytes the feasibility gate prices
        (r"prefix/(k|v)(_scale)?$", (None, None, None, "tensor", None)),
        *base.rules,
    ], default=base.default)


def serve_shardings(mesh, spec: KVCacheSpec, params_abstract,
                    base_rule_set: str = "llama"):
    """NamedShardings for the joint ``{"params", "cache"[, "prefix"]}``
    tree a serve program runs over."""
    rules = kv_cache_rules(base_rule_set)
    abstract = {
        "params": params_abstract,
        "cache": jax.eval_shape(lambda: init_kv_cache(spec)),
    }
    if spec.prefix_pool_pages > 0:
        abstract["prefix"] = jax.eval_shape(
            lambda: init_prefix_pool(spec))
    return rules.tree_shardings(mesh, abstract)


# -- host-side slot surgery (retune across a slot-count change) --------------


def migrate_slots_host(host_cache: Dict[str, Any], old_spec: KVCacheSpec,
                       new_spec: KVCacheSpec,
                       slot_map: Dict[int, int]) -> Dict[str, Any]:
    """Repack a HOST (numpy) cache snapshot into a new slot count:
    ``slot_map`` maps old slot -> new slot for every live request; the
    rest of the new pool is zeros. Page geometry (T, KV, HD, precision)
    must match — a retune changes the SLOT dimension only."""
    import numpy as np

    if (old_spec.max_seq, old_spec.precision) != (
            new_spec.max_seq, new_spec.precision):
        raise ValueError("migrate_slots_host only remaps the slot dim")
    out: Dict[str, Any] = {}
    for name, leaf in host_cache.items():
        arr = np.asarray(leaf)
        if name == "length":
            fresh = np.zeros((new_spec.num_slots,), arr.dtype)
            for old, new in slot_map.items():
                fresh[new] = arr[old]
        else:
            fresh = np.zeros(
                (arr.shape[0], new_spec.num_slots) + arr.shape[2:],
                arr.dtype)
            if name.endswith("_scale"):
                fresh[:] = 1.0
            for old, new in slot_map.items():
                fresh[:, new] = arr[:, old]
        out[name] = fresh
    return out
