"""ServeEngine + ServeExecutor — continuous-batching decode on the
training runtime.

``ServeEngine`` mirrors ``trainer.elastic.ElasticTrainer`` knob for
knob: compiled serve programs (decode step + prefill chunk) live in a
topology+knob program cache, ``prewarm`` standby-compiles a survivor
world or a candidate knob set (executing one dummy step — jit is lazy),
and ``live_resize`` is the PR 5 drain → host-DRAM snapshot → rebuild →
``device_put``-reshard path applied to ``{"params", "cache"}`` instead
of a TrainState. A previously-seen serving topology is ZERO recompiles.

``ServeExecutor`` is the PR 3 async-window skeleton re-aimed at decode:
a fixed-shape slot batch (``serve_slots``), per-step admit/evict slot
swaps through index ops (no recompiles as the active set churns),
prefill chunked INTO the decode stream so a long prompt cannot stall
the batch, and a bounded in-flight window of decode dispatches whose
token materialization lags — greedy sampling happens ON DEVICE, so
step k+1 never waits on step k's host sync.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.serving.kv_cache import (
    KVCacheSpec,
    copy_page_to_pool,
    copy_page_to_slot,
    init_kv_cache,
    init_prefix_pool,
    migrate_slots_host,
    serve_shardings,
)
from dlrover_tpu.serving.prefix_index import PrefixIndex
from dlrover_tpu.serving.spec_decode import NgramProposer
from dlrover_tpu.telemetry import (
    EventKind,
    SpanName,
    emit_event,
    get_registry,
    names as tm,
    span,
)
from dlrover_tpu.telemetry.metrics import LATENCY_BUCKETS
from dlrover_tpu.telemetry.trace_context import trace_scope

logger = get_logger("serving.engine")


@dataclass
class ServeProgram:
    """One compiled serving world: the jitted decode/prefill programs
    plus everything needed to lay state out on its mesh."""

    decode: Callable
    prefill: Callable
    mesh: Any
    shardings: Dict[str, Any]  # {"params": ..., "cache"[, "prefix"]: ...}
    spec: KVCacheSpec
    config: Any
    strategy: Any
    prefill_chunk: int
    # prefix-pool page copies (None when the pool is off): ONE compiled
    # program each, reused for every hit length — the indices are
    # traced scalars, so an H-page hit is H calls, zero recompiles
    admit_copy: Optional[Callable] = None
    publish_copy: Optional[Callable] = None
    # speculative decode: the batched K-position verify program and
    # the draft length K it was compiled for (None/0 = spec off). K is
    # STATIC per program — mixed per-slot draft lengths ride the
    # n_draft valid mask, so steady state never recompiles.
    verify: Optional[Callable] = None
    spec_k: int = 0

    def compiled_cache_size(self) -> int:
        total = 0
        for fn in (self.decode, self.prefill, self.admit_copy,
                   self.publish_copy, self.verify):
            if fn is None:
                continue
            inner = getattr(fn, "__wrapped__", fn)
            size = getattr(inner, "_cache_size", None)
            if callable(size):
                total += int(size())
        return total


def _resolve_knob(value, name: str, default):
    if value is not None:
        return value
    from dlrover_tpu.common.config import get_context

    return getattr(get_context(), name, default)


def _fit_prefill_chunk(requested: int, pool_depth: int) -> int:
    """The largest divisor of the pool depth <= the requested chunk.

    Chunk cursors advance in whole chunks (the last chunk is the only
    partial one), so start positions are multiples of C — with C | T
    every padded write window [start, start+C) fits the pool. Without
    this, a window crossing the pool end would be CLAMPED by
    ``dynamic_update_slice`` (e.g. T=48, C=32, a 40-token prompt:
    chunk 2's start=32 clamps to 16), silently shifting the chunk onto
    — and destroying — earlier pages while the attention mask still
    uses the unclamped positions."""
    want = max(1, min(int(requested), int(pool_depth)))
    for cand in range(want, 0, -1):
        if pool_depth % cand == 0:
            return cand
    return 1


class ServeEngine:
    """Owns (config, compiled serve programs, params, cache) across
    world changes and knob retunes — the serving twin of
    ``ElasticTrainer``."""

    def __init__(self, config, strategy=None, serve_slots: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 kv_precision: Optional[str] = None,
                 max_seq: int = 0, page_size: int = 16,
                 prefix_pool_pages: Optional[int] = None,
                 spec_draft_len: Optional[int] = None,
                 devices=None):
        from dlrover_tpu.parallel.strategy import Strategy
        from dlrover_tpu.serving.kv_cache import resolve_kv_precision

        self._config = config
        self._base_strategy = strategy or Strategy(rule_set="llama")
        self.serve_slots = max(1, int(_resolve_knob(
            serve_slots, "serve_slots", 8)))
        self.kv_precision = resolve_kv_precision(kv_precision)
        self._max_seq = int(max_seq or config.max_seq_len)
        self._page_size = int(page_size)
        import math as _math

        self._pool_depth = self._page_size * max(
            1, _math.ceil(self._max_seq / self._page_size))
        self.prefill_chunk = _fit_prefill_chunk(
            int(_resolve_knob(prefill_chunk, "serve_prefill_chunk",
                              32)), self._pool_depth)
        self.prefix_pool_pages = max(0, int(_resolve_knob(
            prefix_pool_pages, "serve_prefix_pool_pages", 0)))
        # serve_spec_enabled is the master switch: when off, the draft
        # length is pinned to 0 no matter what the knob/optimizer says
        # (the optimizer also refuses to enumerate K under the same
        # gate, but the engine enforces it locally)
        self.spec_enabled = bool(_resolve_knob(
            None, "serve_spec_enabled", True))
        self.spec_draft_len = (max(0, int(_resolve_knob(
            spec_draft_len, "serve_spec_draft_len", 0)))
            if self.spec_enabled else 0)
        self._devices = list(devices) if devices is not None else None
        self._initial_devices: Optional[int] = None
        self._programs: "collections.OrderedDict[str, ServeProgram]" = (
            collections.OrderedDict()
        )
        self._program_cache_cap = 4
        self.compile_count = 0
        self.program: Optional[ServeProgram] = None
        self.params = None
        self.cache = None
        # shared prefix pool: device pages + the host radix index that
        # owns their meaning (None while the knob is 0)
        self.pool = None
        self.prefix_index: Optional[PrefixIndex] = None

    # -- program cache -------------------------------------------------------

    def _spec(self) -> KVCacheSpec:
        return KVCacheSpec.from_model(
            self._config, num_slots=self.serve_slots,
            max_seq=self._max_seq, page_size=self._page_size,
            precision=self.kv_precision,
            prefix_pool_pages=self.prefix_pool_pages,
        )

    def _resolved_strategy(self, num_devices: int):
        return self._base_strategy.adjust_to_world(
            num_devices, prev_num_devices=self._initial_devices)

    def _program_key(self, devices: list, strategy) -> str:
        from dlrover_tpu.parallel.mesh import mesh_axes_key, topology_key

        return (
            topology_key(devices)
            + f"|slots={self.serve_slots}"
            + f"|pc={self.prefill_chunk}"
            + f"|mesh={mesh_axes_key(strategy.mesh)}"
            + f"|kvp={self.kv_precision}"
            + f"|ppp={self.prefix_pool_pages}"
            + f"|spec={self.spec_draft_len}"
        )

    def _build(self, devices: Optional[list]) -> ServeProgram:
        import jax

        actual = list(devices) if devices else jax.devices()
        num = len(actual)
        if self._initial_devices is None:
            self._initial_devices = num
        strategy = self._resolved_strategy(num)
        key = self._program_key(actual, strategy)
        reg = get_registry()
        cached = self._programs.get(key)
        if cached is not None:
            self._programs.move_to_end(key)
            reg.counter(
                tm.PROGRAM_CACHE_HITS,
                help="rebuilds served from the compiled-program cache "
                     "(zero recompiles)").inc()
            logger.info("serve program cache hit for %d devices", num)
            return cached
        reg.counter(tm.PROGRAM_CACHE_MISSES,
                    help="rebuilds that had to compile").inc()
        program = self._compile(actual, strategy)
        self.compile_count += 1
        self._programs[key] = program
        while len(self._programs) > self._program_cache_cap:
            self._programs.popitem(last=False)
        return program

    def _compile(self, devices: list, strategy) -> ServeProgram:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from dlrover_tpu.models import llama

        config = self._config
        spec = self._spec()
        mesh = strategy.mesh.build(devices)
        params_abstract = jax.eval_shape(
            lambda r: llama.init(r, config), jax.random.PRNGKey(0))
        shardings = serve_shardings(
            mesh, spec, params_abstract,
            base_rule_set=strategy.rule_set)
        replicated = NamedSharding(mesh, PartitionSpec())

        def decode_fn(params, cache, tokens, active):
            return llama.decode_step(params, cache, tokens, active,
                                     config, spec)

        def prefill_fn(params, cache, tokens, slot, start, n_valid):
            cache, last_logits = llama.prefill_chunk(
                params, cache, tokens, slot, start, n_valid, config,
                spec)
            # the final chunk's first generated token comes out ON
            # DEVICE: the executor folds it straight into the decode
            # batch, so admission never pays a blocking host argmax
            # sync over the vocab-sized logits
            first = jnp.argmax(last_logits).astype(jnp.int32)
            return cache, last_logits, first

        decode = jax.jit(
            decode_fn,
            in_shardings=(shardings["params"], shardings["cache"],
                          replicated, replicated),
            out_shardings=(replicated, replicated, shardings["cache"]),
            donate_argnums=(1,),
        )
        prefill = jax.jit(
            prefill_fn,
            in_shardings=(shardings["params"], shardings["cache"],
                          replicated, replicated, replicated,
                          replicated),
            out_shardings=(shardings["cache"], replicated,
                           replicated),
            donate_argnums=(1,),
        )
        verify = None
        spec_k = int(self.spec_draft_len)
        if spec_k > 0:
            def verify_fn(params, cache, tokens, active, n_draft):
                return llama.verify_step(params, cache, tokens,
                                         active, n_draft, config,
                                         spec)

            verify = jax.jit(
                verify_fn,
                in_shardings=(shardings["params"],
                              shardings["cache"], replicated,
                              replicated, replicated),
                out_shardings=(replicated, replicated, replicated,
                               shardings["cache"]),
                donate_argnums=(1,),
            )
        admit_copy = publish_copy = None
        if spec.prefix_pool_pages > 0:
            def admit_fn(cache, pool, slot, dst_start, src_page):
                return copy_page_to_slot(cache, pool, slot, dst_start,
                                         src_page, spec)

            def publish_fn(pool, cache, slot, src_start, dst_page):
                return copy_page_to_pool(pool, cache, slot, src_start,
                                         dst_page, spec)

            admit_copy = jax.jit(
                admit_fn,
                in_shardings=(shardings["cache"], shardings["prefix"],
                              replicated, replicated, replicated),
                out_shardings=shardings["cache"],
                donate_argnums=(0,),
            )
            publish_copy = jax.jit(
                publish_fn,
                in_shardings=(shardings["prefix"], shardings["cache"],
                              replicated, replicated, replicated),
                out_shardings=shardings["prefix"],
                donate_argnums=(0,),
            )
        logger.info(
            "serve program compiled: %d devices, slots=%d chunk=%d "
            "kv=%s spec_k=%d mesh=%s", len(devices), spec.num_slots,
            self.prefill_chunk, spec.precision, spec_k,
            dict(zip(mesh.axis_names, mesh.devices.shape)),
        )
        return ServeProgram(
            decode=decode, prefill=prefill, mesh=mesh,
            shardings=shardings, spec=spec, config=config,
            strategy=strategy, prefill_chunk=self.prefill_chunk,
            admit_copy=admit_copy, publish_copy=publish_copy,
            verify=verify, spec_k=spec_k,
        )

    # -- lifecycle -----------------------------------------------------------

    def prepare(self, params) -> None:
        """Compile for the current world and lay ``params`` + a fresh
        pool out on its mesh. ``params`` may be host numpy, a live
        training tree, or a promoted checkpoint's params."""
        import jax

        self.program = self._build(self._devices)
        self.params = jax.device_put(
            params, self.program.shardings["params"])
        self.cache = jax.device_put(
            _host_zero_cache(self.program.spec),
            self.program.shardings["cache"])
        self.reset_prefix()
        jax.block_until_ready(self.params)

    def fresh_cache(self):
        import jax

        return jax.device_put(
            _host_zero_cache(self.program.spec),
            self.program.shardings["cache"])

    def reset_prefix(self):
        """(Re)build an EMPTY prefix pool + index for the active
        program — prepare, a pool-knob retune, and bench legs that
        want identical cold-pool starting lines all land here."""
        import jax

        spec = self.program.spec
        if spec.prefix_pool_pages <= 0:
            self.pool = None
            self.prefix_index = None
            return
        self.pool = jax.device_put(
            _host_zero_pool(spec), self.program.shardings["prefix"])
        self.prefix_index = PrefixIndex(
            spec.page_size, spec.prefix_pool_pages)

    # -- promotion (checkpoint -> serving, no cold start) --------------------

    def load_from_snapshot(self, snapshot) -> None:
        """Promote a live trainer's ``HostSnapshot`` (or any TrainState-
        shaped host tree) into the serving shardings: the train+serve
        colocation path — one ``device_put``, no storage round-trip, no
        cold start."""
        import jax

        tree = getattr(snapshot, "tree", snapshot)
        params = getattr(tree, "params", tree)
        if self.program is None:
            self.prepare(params)
            return
        self.params = jax.device_put(
            params, self.program.shardings["params"])
        jax.block_until_ready(self.params)

    def load_from_checkpoint(self, ckpt_dir: str, init_fn, optimizer,
                             grad_precision: str = "bf16"):
        """Promote a TRAINING checkpoint into the serving tier: the
        TrainState restores against the SERVING param shardings
        directly (Orbax reshard-on-load — the Universal-Checkpointing
        move), so a differently-sharded serving world starts warm.
        Returns the restored step (None when no checkpoint exists)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from dlrover_tpu.checkpoint import (
            ElasticCheckpointManager,
            abstract_like,
        )
        from dlrover_tpu.parallel.accelerate import TrainState

        if self.program is None:
            self.program = self._build(self._devices)

        def make_state(r):
            params = init_fn(r)
            residual = (jax.tree.map(jnp.zeros_like, params)
                        if grad_precision != "bf16" else None)
            return TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=optimizer.init(params),
                wire_residual=residual,
            )

        abstract = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        repl = NamedSharding(self.program.mesh, PartitionSpec())
        sharding_tree = TrainState(
            step=repl,
            params=self.program.shardings["params"],
            opt_state=jax.tree.map(lambda _: repl, abstract.opt_state),
            wire_residual=(
                jax.tree.map(lambda _: repl, abstract.wire_residual)
                if abstract.wire_residual is not None else None),
        )
        target = abstract_like(abstract, sharding_tree)
        mgr = ElasticCheckpointManager(ckpt_dir)
        try:
            out = mgr.restore(target)
        finally:
            mgr.close()
        if out is None:
            return None
        self.params = out["state"].params
        if self.cache is None:
            self.cache = self.fresh_cache()
        logger.info("promoted training checkpoint step %d into the "
                    "serving tier", out["step"])
        return out["step"]

    # -- elasticity ----------------------------------------------------------

    def prewarm(self, devices=None, serve_slots: Optional[int] = None,
                prefill_chunk: Optional[int] = None,
                prefix_pool_pages: Optional[int] = None,
                spec_draft_len: Optional[int] = None,
                execute: bool = True) -> bool:
        """Standby-compile the program for a topology or knob set we
        may swap to, executing one dummy decode step AND one dummy
        prefill chunk (plus one admit/publish page copy when the
        prefix pool is on — jit is lazy) — so the live resize / retune
        that follows pays ZERO recompiles. Does not switch the active
        program. Returns True when a compile happened."""
        import jax
        import jax.numpy as jnp

        prev_slots, prev_chunk = self.serve_slots, self.prefill_chunk
        prev_ppp = self.prefix_pool_pages
        prev_spec_k = self.spec_draft_len
        if serve_slots is not None:
            self.serve_slots = max(1, int(serve_slots))
        if prefill_chunk is not None:
            self.prefill_chunk = _fit_prefill_chunk(
                int(prefill_chunk), self._pool_depth)
        if prefix_pool_pages is not None:
            self.prefix_pool_pages = max(0, int(prefix_pool_pages))
        if spec_draft_len is not None:
            self.spec_draft_len = (max(0, int(spec_draft_len))
                                   if self.spec_enabled else 0)
        try:
            before = self.compile_count
            program = self._build(
                list(devices) if devices is not None else self._devices)
            compiled = self.compile_count > before
            if execute and compiled and self.params is not None:
                params = jax.device_put(
                    self.params, program.shardings["params"])
                cache = jax.device_put(
                    _host_zero_cache(program.spec),
                    program.shardings["cache"])
                s = program.spec.num_slots
                tokens = jnp.zeros((s,), jnp.int32)
                active = jnp.zeros((s,), bool)
                _nt, _lg, cache = program.decode(
                    params, cache, tokens, active)
                chunk = jnp.zeros((program.prefill_chunk,), jnp.int32)
                cache, _ll, _ft = program.prefill(
                    params, cache, chunk, jnp.int32(0), jnp.int32(0),
                    jnp.int32(1))
                if program.verify is not None:
                    draft = jnp.zeros((s, program.spec_k + 1),
                                      jnp.int32)
                    n_draft = jnp.zeros((s,), jnp.int32)
                    _g, _a, _nt, cache = program.verify(
                        params, cache, draft, active, n_draft)
                if program.admit_copy is not None:
                    pool = jax.device_put(
                        _host_zero_pool(program.spec),
                        program.shardings["prefix"])
                    cache = program.admit_copy(
                        cache, pool, jnp.int32(0), jnp.int32(0),
                        jnp.int32(0))
                    pool = program.publish_copy(
                        pool, cache, jnp.int32(0), jnp.int32(0),
                        jnp.int32(0))
                    jax.block_until_ready(pool)
                jax.block_until_ready(cache)
                logger.info("prewarmed standby serve program (%d "
                            "devices, slots=%d)", len(
                                program.mesh.devices.flatten()), s)
        finally:
            self.serve_slots = prev_slots
            self.prefill_chunk = prev_chunk
            self.prefix_pool_pages = prev_ppp
            self.spec_draft_len = prev_spec_k
        return compiled

    def snapshot(self):
        """Host-DRAM copy of ``{"params", "cache"}`` — the resize
        source. In-flight slots' KV pages ride it to the survivor
        world, which is what lets leased requests continue instead of
        restarting from their prompts."""
        from dlrover_tpu.checkpoint import HostSnapshot

        tree = {"params": self.params, "cache": self.cache}
        if self.pool is not None:
            # the prefix pool rides the resize with the slot pages: the
            # host index stays valid (it names pool page ids, and every
            # page's bytes survive the reshard), so pinned in-flight
            # hits and future matches carry straight across
            tree["prefix"] = self.pool
        return HostSnapshot.take(tree, kind="serving")

    def live_resize(self, devices=None, snapshot=None,
                    reason: str = "") -> int:
        """Drain (caller) → snapshot → rebuild (program cache; zero
        recompiles when prewarmed) → reshard params AND live KV pages
        onto the survivor world. Returns the number of programs
        compiled (0 = the prewarmed fast path)."""
        import jax

        old_n = (self.program.mesh.devices.size
                 if self.program is not None else 0)
        t0 = time.monotonic()
        emit_event(EventKind.SERVE_RESIZE_BEGIN, world_from=old_n,
                   reason=reason)
        with span(SpanName.LIVE_RESHARD, world_from=old_n):
            if snapshot is None:
                snapshot = self.snapshot()
            self._devices = list(devices) if devices is not None else None
            compiles_before = self.compile_count
            self.program = self._build(self._devices)
            targets = {
                "params": self.program.shardings["params"],
                "cache": self.program.shardings["cache"],
            }
            snap_tree = getattr(snapshot, "tree", None) or {}
            carry_pool = ("prefix" in snap_tree
                          and "prefix" in self.program.shardings)
            if carry_pool:
                targets["prefix"] = self.program.shardings["prefix"]
            state = snapshot.restore(targets)
            self.params, self.cache = state["params"], state["cache"]
            if carry_pool:
                self.pool = state["prefix"]
            elif self.program.spec.prefix_pool_pages > 0:
                # a snapshot without pool pages (e.g. taken before the
                # knob turned on) cannot carry the index: rebuild clean
                self.reset_prefix()
            else:
                self.pool = None
                self.prefix_index = None
            jax.block_until_ready(self.cache)
        n = self.program.mesh.devices.size
        recompiled = self.compile_count - compiles_before
        seconds = time.monotonic() - t0
        reg = get_registry()
        reg.counter(
            tm.SERVE_RESIZES,
            help="serving worlds resized live (no dropped requests)"
        ).inc()
        reg.histogram(
            tm.SERVE_RESIZE_TIME,
            help="drain -> snapshot -> reshard wall seconds (serving)",
        ).observe(seconds)
        emit_event(EventKind.SERVE_RESIZE_DONE, world_from=old_n,
                   world_to=int(n), reshard_seconds=round(seconds, 3),
                   recompiled=recompiled)
        logger.info("serve resize %d -> %d devices in %.2fs (%s)",
                    old_n, n, seconds,
                    "cache hit" if not recompiled else "recompiled")
        return recompiled

    def retune(self, serve_slots: Optional[int] = None,
               prefill_chunk: Optional[int] = None,
               prefix_pool_pages: Optional[int] = None,
               spec_draft_len: Optional[int] = None,
               slot_map: Optional[Dict[int, int]] = None) -> int:
        """Apply optimizer-chosen serve knobs on the current world
        through the program cache (drain first — the caller owns the
        window). A slot-count change repacks live slots host-side via
        ``slot_map`` (old -> new); prefill_chunk swaps are pure program
        swaps. Failure restores the previous knobs and re-raises.

        Prefix-pool discipline: a POOL-SIZE change rebuilds the pool
        empty (page ids mean nothing across capacities) and a
        PREFILL-CHUNK change flushes the index — published page bytes
        depend on the chunk windows that computed them, so pages
        published under the old grain would break the bitwise-
        continuation oracle under the new one. A slot-only retune
        carries pool and index untouched (the pool has no slot
        dimension). Flush/rebuild cannot dangle refcounts: in-flight
        handles hold the orphaned nodes and release into them."""
        import jax

        prev_slots, prev_chunk = self.serve_slots, self.prefill_chunk
        prev_ppp = self.prefix_pool_pages
        prev_spec_k = self.spec_draft_len
        prev_program = self.program
        old_spec = self.program.spec if self.program else None
        try:
            if serve_slots is not None:
                self.serve_slots = max(1, int(serve_slots))
            if prefill_chunk is not None:
                self.prefill_chunk = _fit_prefill_chunk(
                    int(prefill_chunk), self._pool_depth)
            if prefix_pool_pages is not None:
                self.prefix_pool_pages = max(0, int(prefix_pool_pages))
            if spec_draft_len is not None:
                # a K-only retune is the cheapest knob in the family:
                # K lives in the PROGRAM (tokens shape), not the
                # KVCacheSpec, so the pure-swap fast path below
                # applies — live params and pages stay put
                self.spec_draft_len = (max(0, int(spec_draft_len))
                                       if self.spec_enabled else 0)
            compiles_before = self.compile_count
            new_program = self._build(self._devices)
            chunk_changed = (prev_program is not None
                             and new_program.prefill_chunk
                             != prev_program.prefill_chunk)
            if old_spec is not None and new_program.spec == old_spec:
                # a pure PROGRAM swap (chunk-only retune): the pool
                # spec, shardings and devices are unchanged, so the
                # live params and KV pages are already laid out for
                # the new program — no host round-trip of the whole
                # state inside the serving drain
                self.program = new_program
                if chunk_changed and self.prefix_index is not None:
                    self.prefix_index.flush()
                return self.compile_count - compiles_before
            host = jax.device_get(
                {"params": self.params, "cache": self.cache})
            self.program = new_program
            cache_host = host["cache"]
            if old_spec is not None and \
                    old_spec.num_slots != self.program.spec.num_slots:
                cache_host = migrate_slots_host(
                    cache_host, old_spec, self.program.spec,
                    slot_map or {})
            self.params = jax.device_put(
                host["params"], self.program.shardings["params"])
            self.cache = jax.device_put(
                cache_host, self.program.shardings["cache"])
            jax.block_until_ready(self.cache)
            if self.prefix_pool_pages != prev_ppp:
                self.reset_prefix()
            elif chunk_changed and self.prefix_index is not None:
                self.prefix_index.flush()
            return self.compile_count - compiles_before
        except Exception:
            self.serve_slots = prev_slots
            self.prefill_chunk = prev_chunk
            self.prefix_pool_pages = prev_ppp
            self.spec_draft_len = prev_spec_k
            # the ACTIVE program too, not just the knobs: _build may
            # have swapped it before the device_put failed (OOM on a
            # wider pool) — leaving the new-spec program over the
            # old-shape cache would shape-mismatch every later call
            # and wipe the executor's slot bookkeeping at the next
            # _ensure_prepared
            self.program = prev_program
            raise

    # -- shared prefix pool (radix-indexed KV reuse, copy-on-admit) ----------

    def prefix_enabled(self) -> bool:
        return (self.program is not None
                and self.program.spec.prefix_pool_pages > 0
                and self.pool is not None
                and self.prefix_index is not None)

    def _prefix_align(self) -> int:
        """Matched prefixes round DOWN to this token grain —
        lcm(page_size, prefill_chunk) — so the unmatched tail's chunk
        windows start at the SAME multiples of the chunk a full
        prefill uses: the reused continuation is then the same
        compiled invocations over the same bytes, which is what makes
        it bitwise on f32/bf16 pools (and keeps every padded write
        window inside the pool — the dynamic_update_slice clamp
        hazard ``_fit_prefill_chunk`` documents cannot arise)."""
        import math as _math

        pg = self.program.spec.page_size
        c = self.program.prefill_chunk
        return pg * c // _math.gcd(pg, c)

    def prefix_match(self, prompt: List[int]):
        """Walk the index for the longest usable prefix of ``prompt``.
        Returns ``(matched_tokens, handle)`` with the matched chain
        PINNED, or ``(0, None)``. The match is capped strictly below
        ``len(prompt)`` — a final prefill chunk must always run (its
        last logits seed the first generated token)."""
        if not self.prefix_enabled():
            return 0, None
        align = self._prefix_align()
        pg = self.program.spec.page_size
        cap_tokens = ((len(prompt) - 1) // align) * align
        if cap_tokens <= 0:
            return 0, None
        handle = self.prefix_index.match(
            prompt, max_pages=cap_tokens // pg,
            align_pages=align // pg)
        if handle is None:
            return 0, None
        return handle.tokens, handle

    def prefix_admit(self, slot: int, handle) -> None:
        """Copy the matched pool pages into the slot's leading rows —
        H pages = H calls of ONE compiled copy program."""
        import jax.numpy as jnp

        program = self.program
        pg = program.spec.page_size
        for i, page_id in enumerate(handle.pages):
            self.cache = program.admit_copy(
                self.cache, self.pool, jnp.int32(slot),
                jnp.int32(i * pg), jnp.int32(page_id))

    def prefix_publish(self, slot: int, prompt: List[int]):
        """Index + copy the full pages of a COMPLETED prefill into the
        pool (pages already present are skipped; a full pool skips the
        rest — logged/counted, never raised). Returns
        ``(pages_published, pages_evicted)``."""
        import jax.numpy as jnp

        if not self.prefix_enabled():
            return 0, 0
        program = self.program
        pg = program.spec.page_size
        evict_before = self.prefix_index.evictions
        new_pages = self.prefix_index.publish(prompt)
        for idx, page_id in new_pages:
            self.pool = program.publish_copy(
                self.pool, self.cache, jnp.int32(slot),
                jnp.int32(idx * pg), jnp.int32(page_id))
        return (len(new_pages),
                self.prefix_index.evictions - evict_before)

    def prefix_release(self, handle) -> None:
        """Unpin a hit's pages (idempotent; survives flush/rebuild)."""
        if self.prefix_index is not None:
            self.prefix_index.release(handle)
        elif handle is not None:
            handle.released = True

    def prefix_stats(self) -> Dict[str, Any]:
        """Cumulative pool counters + current occupancy (empty when
        the pool is off) — the SERVE_END summary and the hit-rate the
        config report feeds the optimizer's pricing."""
        if self.prefix_index is None:
            return {}
        out = dict(self.prefix_index.stats())
        out["pool_bytes"] = self.program.spec.prefix_pool_bytes()
        out["used_bytes"] = (out["used_pages"]
                             * self.program.spec.prefix_page_bytes())
        return out


def _host_zero_cache(spec: KVCacheSpec):
    """Zero-filled host cache (numpy — no device allocation until the
    device_put lays it out shard by shard)."""
    import jax

    return jax.tree.map(
        lambda a: np.zeros(a.shape, a.dtype),
        jax.eval_shape(lambda: init_kv_cache(spec)),
    )


def _host_zero_pool(spec: KVCacheSpec):
    """Zero-filled host prefix pool (the ``_host_zero_cache`` twin)."""
    import jax

    return jax.tree.map(
        lambda a: np.zeros(a.shape, a.dtype),
        jax.eval_shape(lambda: init_prefix_pool(spec)),
    )


# -- the continuous-batching executor ----------------------------------------

# per-process executor sequence: SERVE_START/END events carry it so
# the forensic slot-ledger derivation can tell "one executor's
# cumulative ledger reported twice" from "two executors' ledgers"
_serve_seq = itertools.count(1)


@dataclass
class ServeRequestState:
    """Host-side bookkeeping for one leased request in a slot."""

    request_id: str
    prompt: List[int]
    max_new_tokens: int
    eos_id: int = -1
    cursor: int = 0            # prompt tokens prefilled so far
    generated: List[int] = field(default_factory=list)
    t_admit: float = 0.0
    t_first_token: Optional[float] = None
    # the per-request trace id minted at Router.submit (or locally for
    # router-less submissions): every lifecycle event this worker
    # emits for the request carries it
    trace_id: str = ""
    # local-queue submissions stamp their enqueue time so the worker
    # can report queue-wait without a router (bench/local mode)
    t_submit: Optional[float] = None
    # prompt tokens whose KV pages came from the shared prefix pool
    # (copy-on-admit) instead of prefill, and the pin over those pages
    # — held admit -> completion, released idempotently
    prefix_hit_tokens: int = 0
    prefix_handle: Any = None
    # speculative decode: the per-request draft proposer (host-only
    # suffix index — it moves with the state object across slot
    # remaps) and the request's drafted/accepted ledger columns.
    # drafted - accepted = wasted by construction, checked end to end.
    draft_state: Any = None
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0


@dataclass
class _InflightDecode:
    tokens: Any                       # device [S] next-token array
    owners: Dict[int, str]            # slot -> request_id at dispatch
    # slots whose FIRST token (the on-device prefill argmax) rides
    # this entry: materialization appends it, stamps TTFT and runs
    # finish detection — the host sync admission used to pay moved
    # behind the window
    firsts: Optional[Dict[int, str]] = None


class ServeExecutor:
    """Continuous batching over a fixed slot batch.

    One loop iteration: (boundary work: plans/resizes/admission) → at
    most one prefill chunk per admitting slot → ONE decode step for the
    whole batch → lagged materialization of the oldest in-flight decode
    (the PR 3 window, ``serve_window``). Greedy tokens feed back on
    device; the host only ever reads tokens that are already
    ``serve_window`` steps old, so Python/RPC overhead never drains the
    device queue.

    ``admission="static"`` is the comparison mode ``bench --mode
    serve`` pairs against: a full batch admits together and the next
    batch waits for the LAST request of the current one — the classic
    static-batching tail every mixed-length workload pays.
    """

    def __init__(self, engine: ServeEngine, router_client=None,
                 admission: str = "continuous",
                 serve_window: Optional[int] = None,
                 eos_id: int = -1, max_new_default: int = 16,
                 plan_poll_secs: Optional[float] = None,
                 registry=None, report_hook=None,
                 spec_proposer: Optional[Callable] = None):
        from dlrover_tpu.common.config import get_context

        ctx = get_context()
        self._engine = engine
        self._client = router_client
        self._admission = admission
        self._window_cap = max(0, int(_resolve_knob(
            serve_window, "serve_window", 2)))
        self._eos_default = int(eos_id)
        self._max_new_default = int(max_new_default)
        self._plan_poll = float(
            plan_poll_secs if plan_poll_secs is not None
            else getattr(ctx, "plan_poll_secs", 30.0))
        self._last_plan_poll = 0.0
        self._seen_plan = ""
        self._last_touch = 0.0
        self._local_queue: "collections.deque" = collections.deque()
        self._window: "collections.deque[_InflightDecode]" = (
            collections.deque())
        self._slots: List[Optional[ServeRequestState]] = []
        self._active_host: List[bool] = []
        self._tokens = None
        self._active = None
        self._resize_devices = None
        self._resize_requested = False
        self._resize_trace_id = ""
        self._retune_request: Optional[Dict[str, Any]] = None
        self.completed: List[Dict[str, Any]] = []
        self.decode_steps = 0
        self._local_id_seq = 0
        # speculative decode: a factory producing one proposer PER
        # REQUEST (tests inject deterministic 0%/100%/alternating
        # proposers through it; default is the n-gram prompt-lookup
        # index). Worker-lifetime drafted/accepted totals feed the
        # acceptance-rate gauge and the config report's observed rate.
        self._spec_proposer_factory = spec_proposer
        self._spec_drafted_total = 0
        self._spec_accepted_total = 0
        self._serve_seq = next(_serve_seq)
        # slot-time ledger: every slot-second of the serve loop is
        # charged to exactly ONE class (decode / prefill /
        # admitted_idle / vacant / resize_frozen), so the classes sum
        # to slots x wall by construction — the serving analog of the
        # goodput partition. Accumulated host-side (plain float adds;
        # no registry on this path) and emitted on SERVE_END.
        self._ledger: Dict[str, float] = {
            k: 0.0 for k in ("decode", "prefill", "admitted_idle",
                             "vacant", "resize_frozen")}
        self._ledger_mark: Optional[float] = None
        self._slot_seconds = 0.0
        self._serve_wall = 0.0
        # the ledger is observability, so it pays inside the ≤5%
        # overhead gate: off with the rest of telemetry (resolved at
        # construction, the get_registry() discipline)
        self._ledger_enabled = bool(
            getattr(ctx, "telemetry_enabled", True))
        # a test may pass a private registry to simulate several serve
        # nodes in one process (the NodeRuntimeReportHook discipline)
        reg = registry if registry is not None else get_registry()
        self._c_tokens = reg.counter(
            tm.SERVE_TOKENS, help="tokens generated by this worker")
        self._c_decode = reg.counter(
            tm.SERVE_DECODE_STEPS, help="batched decode steps dispatched")
        self._c_prefill = reg.counter(
            tm.SERVE_PREFILL_CHUNKS, help="prefill chunks dispatched")
        self._c_admitted = reg.counter(
            tm.SERVE_ADMISSIONS, help="requests admitted into slots")
        self._g_occupancy = reg.gauge(
            tm.SERVE_SLOT_OCCUPANCY,
            help="slots holding a live request, after admission")
        self._h_step = reg.histogram(
            tm.SERVE_STEP_TIME, buckets=LATENCY_BUCKETS,
            help="per-decode-step wall seconds")
        self._h_prefill_e2e = reg.histogram(
            tm.SERVE_PREFILL_TIME, buckets=LATENCY_BUCKETS,
            help="admit -> prompt fully prefilled wall seconds")
        # shared prefix pool counters/gauges (flat at zero while the
        # pool knob is off — the registry costs nothing for them)
        self._c_phits = reg.counter(
            tm.SERVE_PREFIX_HITS,
            help="admissions whose leading pages came from the pool")
        self._c_pmisses = reg.counter(
            tm.SERVE_PREFIX_MISSES,
            help="admissions that walked the index and found nothing")
        self._c_pevict = reg.counter(
            tm.SERVE_PREFIX_EVICTIONS,
            help="pool pages LRU-evicted to make room for a publish")
        self._c_psaved = reg.counter(
            tm.SERVE_PREFIX_SAVED_TOKENS,
            help="prefill tokens skipped via copy-on-admit")
        self._g_pool_used = reg.gauge(
            tm.SERVE_PREFIX_POOL_USED_PAGES,
            help="prefix-pool pages currently indexed")
        self._g_pool_bytes = reg.gauge(
            tm.SERVE_PREFIX_POOL_BYTES,
            help="prefix-pool device residency (the HBM-gate charge)")
        # speculative-decode ledger counters (flat at zero while K=0):
        # drafted = accepted + wasted at every grain — per request,
        # per worker, per router job
        self._c_spec_steps = reg.counter(
            tm.SERVE_SPEC_VERIFY_STEPS,
            help="batched multi-token verify steps dispatched")
        self._c_spec_drafted = reg.counter(
            tm.SERVE_SPEC_DRAFTED,
            help="draft tokens proposed into verify steps")
        self._c_spec_accepted = reg.counter(
            tm.SERVE_SPEC_ACCEPTED,
            help="draft tokens accepted (matched the greedy argmax)")
        self._c_spec_wasted = reg.counter(
            tm.SERVE_SPEC_WASTED,
            help="draft tokens rejected by verify (computed, unused)")
        self._g_spec_rate = reg.gauge(
            tm.SERVE_SPEC_ACCEPT_RATE,
            help="accepted/drafted over this worker's lifetime "
                 "(-1 until the first draft)")
        self._g_spec_rate.set(-1.0)
        # SLO-plane node reporting: serve workers ride the SAME
        # NodeRuntimeReport path training workers do, so the master's
        # /metrics carries {node=} serving gauges and the straggler
        # detector judges slow decode workers. Auto-wired when the
        # client can carry it (the executor's NodeRuntimeReportHook
        # discipline); pass an explicit hook to control cadence.
        if report_hook is None and router_client is not None and \
                hasattr(router_client, "report_node_runtime"):
            from dlrover_tpu.serving.slo import ServeRuntimeReportHook

            report_hook = ServeRuntimeReportHook(
                router_client, registry=reg)
        self._report_hook = report_hook or None

    # -- local submission (router-less mode / tests) -------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 0,
               request_id: str = "", eos_id: Optional[int] = None):
        """Enqueue a request on the worker-local queue (no router)."""
        from dlrover_tpu.serving.router import new_request_trace_id

        # a monotonic sequence, never derived from queue/completed
        # lengths: those regress when a request is admitted-but-
        # unfinished, and a colliding id breaks the window's owner
        # guard (two live slots claiming one identity)
        self._local_id_seq += 1
        rid = request_id or f"local-{self._local_id_seq}"
        self._local_queue.append({
            "request_id": rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens
                                  or self._max_new_default),
            "eos_id": (self._eos_default if eos_id is None
                       else int(eos_id)),
            "trace_id": new_request_trace_id(),
            "submit_ts": time.monotonic(),
        })
        return rid

    # -- elasticity hooks ----------------------------------------------------

    def request_resize(self, devices=None, trace_id: str = ""):
        """``trace_id`` threads the incident that caused the resize
        (an SLO scale proposal) onto the SERVE_RESIZE_* events."""
        self._resize_devices = (list(devices)
                                if devices is not None else None)
        self._resize_trace_id = str(trace_id or "")
        self._resize_requested = True

    def request_retune(self, serve_slots: Optional[int] = None,
                       prefill_chunk: Optional[int] = None,
                       prefix_pool_pages: Optional[int] = None,
                       spec_draft_len: Optional[int] = None,
                       plan_id: str = "", prewarm: bool = False):
        self._retune_request = {
            "serve_slots": serve_slots,
            "prefill_chunk": prefill_chunk,
            "prefix_pool_pages": prefix_pool_pages,
            "spec_draft_len": spec_draft_len,
            "plan_id": plan_id,
            "prewarm": bool(prewarm),
        }

    # -- loop ----------------------------------------------------------------

    def _ensure_prepared(self):
        import jax.numpy as jnp

        if self._engine.program is None:
            raise RuntimeError("engine.prepare(params) first")
        s = self._engine.program.spec.num_slots
        if len(self._slots) != s:
            if any(r is not None for r in self._slots):
                # the slot width changed UNDER live requests — a
                # direct engine.retune() between serve() calls.
                # Silently rebuilding would drop those requests (and
                # dangle their router leases); the supported path is
                # request_retune, which repacks them.
                raise RuntimeError(
                    "engine slot width changed with live requests; "
                    "use ServeExecutor.request_retune")
            self._slots = [None] * s
            self._active_host = [False] * s
        if self._tokens is None or int(self._tokens.shape[0]) != s:
            self._tokens = jnp.zeros((s,), jnp.int32)
            self._active = jnp.asarray(self._active_host)

    def _free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is None]

    def _lease(self, n: int) -> List[Dict[str, Any]]:
        out = []
        while n > 0 and self._local_queue:
            out.append(self._local_queue.popleft())
            n -= 1
        if n > 0 and self._client is not None:
            try:
                out.extend(self._client.serve_lease(max_requests=n))
            except Exception:  # noqa: BLE001 — a dead master must not
                # kill serving; the worker drains its admitted slots
                logger.debug("serve lease failed", exc_info=True)
        return out

    def _admit(self):
        free = self._free_slots()
        if not free:
            return
        if self._admission == "static" and len(free) != len(self._slots):
            # static batching: the next batch waits for the WHOLE
            # current batch — the tail continuous batching removes
            return
        leases = self._lease(len(free))
        max_seq = self._engine.program.spec.max_seq
        for req in leases:
            slot = free.pop(0)
            state = ServeRequestState(
                request_id=str(req["request_id"]),
                prompt=[int(t) for t in req["prompt"]],
                max_new_tokens=int(req.get("max_new_tokens")
                                   or self._max_new_default),
                eos_id=int(req.get("eos_id", self._eos_default)),
                trace_id=str(req.get("trace_id", "") or ""),
                t_submit=req.get("submit_ts"),
                t_admit=time.monotonic(),
            )
            if len(state.prompt) + state.max_new_tokens > max_seq:
                # the pool cannot hold this request: evict loudly (a
                # failure-class edge — carries its error code) and
                # complete it as errored so the router never counts it
                # dropped-on-the-floor
                emit_event(
                    EventKind.SERVE_REQUEST_EVICTED,
                    error_code="SERVE_REQUEST_EVICTED",
                    trace_id=state.trace_id,
                    request_id=state.request_id,
                    prompt_tokens=len(state.prompt),
                    max_seq=max_seq,
                )
                self._complete(state, error_code="SERVE_REQUEST_EVICTED")
                continue
            matched, handle = self._engine.prefix_match(state.prompt)
            if handle is not None:
                # copy-on-admit: matched pages land in the slot's
                # leading rows NOW, so the prefill tick below starts at
                # the unmatched tail — same chunk windows a full
                # prefill would run from that cursor (bitwise)
                self._engine.prefix_admit(slot, handle)
                state.cursor = matched
                state.prefix_hit_tokens = matched
                state.prefix_handle = handle
                self._c_phits.inc()
                self._c_psaved.inc(matched)
                emit_event(
                    EventKind.SERVE_PREFIX_HIT,
                    trace_id=state.trace_id,
                    request_id=state.request_id, slot=slot,
                    hit_tokens=matched,
                    prompt_tokens=len(state.prompt),
                )
            elif self._engine.prefix_enabled():
                self._c_pmisses.inc()
            self._slots[slot] = state
            self._c_admitted.inc()
            if not free:
                break
        self._g_occupancy.set(
            sum(1 for r in self._slots if r is not None))
        if self._engine.prefix_enabled():
            stats = self._engine.prefix_stats()
            self._g_pool_used.set(stats.get("used_pages", 0))
            self._g_pool_bytes.set(stats.get("used_bytes", 0))

    def _prefill_tick(self):
        """Dispatch at most ONE chunk per admitting slot, so prefill
        interleaves with the decode stream instead of stalling it."""
        import jax.numpy as jnp

        program = self._engine.program
        c = program.prefill_chunk
        firsts: Dict[int, str] = {}
        for slot, state in enumerate(self._slots):
            if state is None or state.cursor >= len(state.prompt) \
                    or self._active_host[slot]:
                continue
            chunk = state.prompt[state.cursor:state.cursor + c]
            n_valid = len(chunk)
            padded = np.zeros((c,), np.int32)
            padded[:n_valid] = chunk
            with span(SpanName.SERVE_PREFILL, slot=slot):
                self._engine.cache, _last_logits, first_tok = (
                    program.prefill(
                        self._engine.params, self._engine.cache,
                        jnp.asarray(padded), jnp.int32(slot),
                        jnp.int32(state.cursor), jnp.int32(n_valid)))
            self._c_prefill.inc()
            state.cursor += n_valid
            emit_event(
                EventKind.SERVE_PREFILL_CHUNK,
                trace_id=state.trace_id, request_id=state.request_id,
                slot=slot, cursor=state.cursor,
                prompt_tokens=len(state.prompt),
            )
            if state.cursor >= len(state.prompt):
                # a completed prefill publishes its full pages into
                # the prefix pool BEFORE the decode stream can touch
                # the slot (decode appends rows past the prompt; the
                # published pages must be pure prefill output)
                published, evicted = self._engine.prefix_publish(
                    slot, state.prompt)
                if evicted:
                    self._c_pevict.inc(evicted)
                    emit_event(
                        EventKind.SERVE_PREFIX_EVICTED,
                        trace_id=state.trace_id,
                        request_id=state.request_id,
                        pages=evicted,
                    )
                # final chunk: the first token stays ON DEVICE — it
                # lands in the slot's decode-batch row and a firsts
                # window entry carries its identity, so admission no
                # longer blocks on a host argmax sync. TTFT/finish
                # detection happen at materialization (the same lag
                # eos detection already has in the decode stream).
                self._tokens = self._tokens.at[slot].set(first_tok)
                firsts[slot] = state.request_id
                self._active_host[slot] = True
                self._active = jnp.asarray(self._active_host)
        if firsts:
            self._window.append(_InflightDecode(
                tokens=self._tokens, owners={}, firsts=firsts))

    def _finished(self, state: ServeRequestState) -> bool:
        if len(state.generated) >= state.max_new_tokens:
            return True
        return (state.eos_id >= 0 and state.generated
                and state.generated[-1] == state.eos_id)

    def _complete(self, state: ServeRequestState, error_code: str = ""):
        now = time.monotonic()
        # the pin over the hit's pool pages ends with the request
        # (idempotent — a pool flush/rebuild in between is harmless)
        if state.prefix_handle is not None:
            self._engine.prefix_release(state.prefix_handle)
            state.prefix_handle = None
        record = {
            "request_id": state.request_id,
            "tokens": list(state.generated),
            "ttft_s": (round(state.t_first_token - state.t_admit, 6)
                       if state.t_first_token else None),
            "e2e_s": round(now - state.t_admit, 6),
            "error_code": error_code,
            "prefix_hit_tokens": int(state.prefix_hit_tokens),
            "spec_drafted_tokens": int(state.spec_drafted_tokens),
            "spec_accepted_tokens": int(state.spec_accepted_tokens),
        }
        emit_event(
            EventKind.SERVE_REQUEST_DONE,
            trace_id=state.trace_id, request_id=state.request_id,
            tokens=len(state.generated), ttft_s=record["ttft_s"],
            e2e_s=record["e2e_s"],
            done_error_code=error_code or None,
        )
        # local-queue submissions see their queue wait here (the
        # router measures its own at lease time)
        if state.t_submit is not None:
            record["queue_wait_s"] = round(
                state.t_admit - state.t_submit, 6)
        self.completed.append(record)
        self._c_tokens.inc(len(state.generated))
        if self._client is not None:
            wire = {k: v for k, v in record.items()
                    if k != "queue_wait_s"}
            try:
                # the request's trace id rides the gRPC metadata
                # channel, so the router's ingress-side events (the
                # completion record) join the request's lane
                if state.trace_id:
                    with trace_scope(state.trace_id):
                        self._client.serve_complete(**wire)
                else:
                    self._client.serve_complete(**wire)
            except Exception:  # noqa: BLE001 — the router re-leases on
                # lease timeout; a lost completion is re-served, never
                # silently dropped
                logger.warning("serve completion report failed",
                               exc_info=True)

    def _retire(self, slot: int):
        import jax.numpy as jnp

        state = self._slots[slot]
        self._slots[slot] = None
        self._active_host[slot] = False
        self._active = jnp.asarray(self._active_host)
        self._complete(state)

    # -- slot-time ledger ----------------------------------------------------

    def _classify(self) -> List[str]:
        """Per-slot ledger class under the CURRENT host state."""
        out = []
        for i, state in enumerate(self._slots):
            if state is None:
                out.append("vacant")
            elif self._active_host[i]:
                out.append("decode")
            elif state.cursor < len(state.prompt):
                out.append("prefill")
            else:
                # admitted, prompt prefilled, but not decoding — the
                # finish-detection lag / pre-activation gap
                out.append("admitted_idle")
        return out

    def _charge_slots(self, now: float, override: Optional[str] = None,
                      classes: Optional[List[str]] = None):
        """Charge the wall time since the previous mark to the ledger:
        ``dt`` per slot, each slot to exactly one class. ``override``
        charges every slot (the resize/retune freeze); ``classes`` is
        a pre-captured per-slot classification (the prefill interval
        classifies by the state that held DURING it, not the state the
        tick left behind). Classes sum to ∫slots·dt by construction."""
        if not self._ledger_enabled:
            return
        mark = self._ledger_mark
        self._ledger_mark = now
        if mark is None:
            return
        dt = now - mark
        if dt <= 0 or not self._slots:
            return
        self._slot_seconds += dt * len(self._slots)
        if override is not None:
            self._ledger[override] += dt * len(self._slots)
            return
        if classes is None or len(classes) != len(self._slots):
            classes = self._classify()
        for cls in classes:
            self._ledger[cls] += dt

    def slot_ledger(self) -> Dict[str, float]:
        """The accumulated slot-seconds partition plus its invariant
        total (``slot_seconds`` = ∫slots·dt charged so far; the sum of
        the classes, exactly) and the serve-loop wall it partitions."""
        out = {k: round(v, 6) for k, v in self._ledger.items()}
        out["slot_seconds"] = round(self._slot_seconds, 6)
        out["serve_wall_s"] = round(self._serve_wall, 6)
        return out

    def _materialize_oldest(self):
        import jax

        entry = self._window.popleft()
        host = np.asarray(jax.device_get(entry.tokens))
        for slot, rid in (entry.firsts or {}).items():
            state = self._slots[slot]
            if state is None or state.request_id != rid:
                continue
            state.generated.append(int(host[slot]))
            # TTFT means "first token host-visible": stamped here,
            # where a client could first read it, not at dispatch
            state.t_first_token = time.monotonic()
            self._h_prefill_e2e.observe(
                state.t_first_token - state.t_admit)
            emit_event(
                EventKind.SERVE_FIRST_TOKEN,
                trace_id=state.trace_id,
                request_id=state.request_id, slot=slot,
                ttft_s=round(state.t_first_token - state.t_admit, 6),
            )
            if self._finished(state):
                # later entries' tokens for this slot fail the owner
                # guard once retired — the decode step that ran past
                # a one-token request is discarded, never emitted
                self._retire(slot)
        for slot, rid in entry.owners.items():
            state = self._slots[slot]
            if state is None or state.request_id != rid:
                continue  # completed/reassigned meanwhile: stale token
            state.generated.append(int(host[slot]))
            if state.t_first_token is None:
                state.t_first_token = time.monotonic()
            if self._finished(state):
                self._retire(slot)

    def _drain_window(self):
        while self._window:
            self._materialize_oldest()

    # -- speculative decode (n-gram draft + batched verify) ------------------

    def _spec_step(self):
        """ONE verify step for the whole batch: propose up to K draft
        tokens per active slot from its own history (host n-gram
        index), run the compiled ``verify_step`` over K+1 positions,
        then commit the accepted prefix — emitted text is bitwise the
        plain-greedy stream at every acceptance pattern, and the one
        host sync this loop pays per step is amortized over up to K+1
        tokens (the window is firsts-only in spec mode; the caller
        drained it, so host history is current when proposing)."""
        import jax
        import jax.numpy as jnp

        program = self._engine.program
        k = program.spec_k
        s = program.spec.num_slots
        tokens_h = np.zeros((s, k + 1), np.int32)
        n_draft_h = np.zeros((s,), np.int32)
        owners: Dict[int, str] = {}
        for slot, state in enumerate(self._slots):
            if state is None or not self._active_host[slot]:
                continue
            owners[slot] = state.request_id
            tokens_h[slot, 0] = state.generated[-1]
            # the verify step emits up to n+1 tokens: cap the draft so
            # the commit can never run past max_new_tokens (eos inside
            # the accepted prefix truncates host-side below)
            budget = min(k, state.max_new_tokens
                         - len(state.generated) - 1)
            if budget <= 0:
                continue
            if state.draft_state is None:
                factory = self._spec_proposer_factory
                state.draft_state = (factory() if factory is not None
                                     else NgramProposer())
            draft = state.draft_state.propose(
                state.prompt + state.generated, budget)[:budget]
            if draft:
                n = len(draft)
                tokens_h[slot, 1:1 + n] = draft
                n_draft_h[slot] = n
        try:
            with span(SpanName.SERVE_DECODE, step=self.decode_steps):
                greedy_d, accepted_d, next_d, self._engine.cache = (
                    program.verify(
                        self._engine.params, self._engine.cache,
                        jnp.asarray(tokens_h), self._active,
                        jnp.asarray(n_draft_h)))
        except Exception:  # noqa: BLE001 — a failed verify step must
            # not kill serving OR charge the ledger: nothing was
            # committed (the raise happens before buffers are donated
            # to a successfully launched program), so the draft credit
            # is restored by simply not counting it, and the batch
            # falls back to ONE plain decode step — bitwise the same
            # stream, minus the speculation
            logger.warning("verify step failed; falling back to a "
                           "plain decode step", exc_info=True)
            next_tokens, _lg, self._engine.cache = (
                self._engine.program.decode(
                    self._engine.params, self._engine.cache,
                    self._tokens, self._active))
            self._tokens = next_tokens
            host = np.asarray(jax.device_get(next_tokens))
            for slot, rid in owners.items():
                state = self._slots[slot]
                if state is None or state.request_id != rid:
                    continue
                state.generated.append(int(host[slot]))
                if self._finished(state):
                    self._retire(slot)
            return
        self._tokens = next_d
        greedy_h, accepted_h = jax.device_get((greedy_d, accepted_d))
        greedy_h = np.asarray(greedy_h)
        accepted_h = np.asarray(accepted_h)
        self._c_spec_steps.inc()
        for slot, rid in owners.items():
            state = self._slots[slot]
            if state is None or state.request_id != rid:
                continue
            drafted = int(n_draft_h[slot])
            accepted = min(int(accepted_h[slot]), drafted)
            state.spec_drafted_tokens += drafted
            state.spec_accepted_tokens += accepted
            self._spec_drafted_total += drafted
            self._spec_accepted_total += accepted
            if drafted:
                self._c_spec_drafted.inc(drafted)
                self._c_spec_accepted.inc(accepted)
                self._c_spec_wasted.inc(drafted - accepted)
            # commit greedy[0..accepted] — exactly what plain greedy
            # would emit next — truncating at eos/max_new exactly
            # where the serial stream would have stopped
            for i in range(accepted + 1):
                state.generated.append(int(greedy_h[slot, i]))
                if self._finished(state):
                    self._retire(slot)
                    break
        if self._spec_drafted_total:
            self._g_spec_rate.set(self._spec_accepted_total
                                  / self._spec_drafted_total)

    def _apply_resize(self):
        self._resize_requested = False
        devices = self._resize_devices
        self._resize_devices = None
        trace_id = self._resize_trace_id
        self._resize_trace_id = ""
        import jax

        tokens_host = np.asarray(jax.device_get(self._tokens))
        active_host = list(self._active_host)
        if trace_id:
            # the SERVE_RESIZE_* events join the incident (SLO scale
            # proposal) that asked for the resize
            with trace_scope(trace_id):
                self._engine.live_resize(devices, reason="executor")
        else:
            self._engine.live_resize(devices, reason="executor")
        import jax.numpy as jnp

        self._tokens = jnp.asarray(tokens_host)
        self._active_host = active_host
        self._active = jnp.asarray(active_host)

    def _apply_retune(self):
        import jax
        import jax.numpy as jnp

        req = self._retune_request
        self._retune_request = None
        new_slots = req.get("serve_slots")
        new_chunk = req.get("prefill_chunk")
        new_ppp = req.get("prefix_pool_pages")
        new_spec_k = req.get("spec_draft_len")
        plan_id = req.get("plan_id", "")
        if new_chunk is not None:
            fitted = _fit_prefill_chunk(int(new_chunk),
                                        self._engine._pool_depth)
            if fitted != int(new_chunk):
                # the plan's chunk cannot be honored exactly (it does
                # not divide the pool depth): applying the fitted
                # variant while acking the plan would be the PR 11
                # phantom-apply loop — the master re-chooses the
                # unachievable tuple every cooldown window, each cycle
                # a futile drain. Negative-ack so it blacklists.
                logger.warning(
                    "serve plan %s wants prefill_chunk=%s but the "
                    "pool depth %d fits %d; negative-acking", plan_id,
                    new_chunk, self._engine._pool_depth, fitted)
                self._ack_plan(plan_id, apply_failed=True)
                return
            if int(new_chunk) != self._engine.prefill_chunk:
                # a chunk change invalidates IN-FLIGHT prefill
                # cursors: their start positions are multiples of the
                # OLD chunk, and a grown chunk's padded window could
                # cross the pool end (the dynamic_update_slice clamp
                # hazard _fit_prefill_chunk documents). Restart those
                # prompts from 0 — prefill rewrites its pages, so a
                # restart is always safe and bounded by one prompt.
                for slot, state in enumerate(self._slots):
                    if (state is not None
                            and not self._active_host[slot]
                            and state.cursor > 0):
                        state.cursor = 0
        live = [i for i, r in enumerate(self._slots) if r is not None]
        cur_slots = self._engine.program.spec.num_slots
        # host-side slot compaction happens ONLY when the slot width
        # actually changes (the engine migrates the KV pages under the
        # same condition — a chunk-only retune must leave both the
        # pages AND this bookkeeping exactly where they are, or they
        # diverge and every in-flight continuation is garbage)
        slots_changing = (new_slots is not None
                          and int(new_slots) != cur_slots)
        if slots_changing and len(live) > int(new_slots):
            logger.warning(
                "serve retune to %s slots declined: %d live requests",
                new_slots, len(live))
            self._ack_plan(plan_id, apply_failed=True)
            return
        slot_map = ({old: new for new, old in enumerate(live)}
                    if slots_changing else {i: i for i in live})
        tokens_host = np.asarray(jax.device_get(self._tokens))
        if req.get("prewarm"):
            # standby-compile the candidate program BEFORE the swap
            # (the training plan-apply discipline): the retune below
            # then hits the cache and the drained pause pays zero
            # compiles
            try:
                self._engine.prewarm(serve_slots=new_slots,
                                     prefill_chunk=new_chunk,
                                     prefix_pool_pages=new_ppp,
                                     spec_draft_len=new_spec_k)
            except Exception:  # noqa: BLE001 — prewarm is an
                # optimization; the retune still decides the outcome
                logger.warning("serve prewarm failed", exc_info=True)
        try:
            self._engine.retune(
                serve_slots=new_slots,
                prefill_chunk=req.get("prefill_chunk"),
                prefix_pool_pages=new_ppp,
                spec_draft_len=new_spec_k,
                slot_map=slot_map)
        except Exception:  # noqa: BLE001 — a bad plan must not kill
            # serving; the engine restored the previous knobs
            logger.exception("serve retune failed; continuing with the "
                             "previous config")
            self._ack_plan(plan_id, apply_failed=True)
            return
        if slots_changing:
            s = self._engine.program.spec.num_slots
            slots: List[Optional[ServeRequestState]] = [None] * s
            active = [False] * s
            tokens = np.zeros((s,), np.int32)
            for old, new in slot_map.items():
                slots[new] = self._slots[old]
                active[new] = self._active_host[old]
                tokens[new] = tokens_host[old]
            self._slots, self._active_host = slots, active
            self._tokens = jnp.asarray(tokens)
            self._active = jnp.asarray(active)
        self._ack_plan(plan_id)

    def _ack_plan(self, plan_id: str, apply_failed: bool = False):
        if not plan_id or self._client is None or not hasattr(
                self._client, "report_serve_config"):
            return
        try:
            self._report_config(plan_id=plan_id,
                                apply_failed=apply_failed)
        except Exception:  # noqa: BLE001
            logger.debug("serve plan ack failed", exc_info=True)

    def _report_config(self, plan_id: str = "",
                       apply_failed: bool = False):
        if self._client is None or not hasattr(
                self._client, "report_serve_config"):
            return
        program = self._engine.program
        stats = self._engine.prefix_stats()
        looked = stats.get("hits", 0) + stats.get("misses", 0)
        # -1 = "no observation yet": the optimizer then falls back to
        # the serve_prefix_expected_hit_rate prior instead of pricing
        # a cold pool as worthless forever
        hit_rate = (stats["hits"] / looked if stats and looked
                    else -1.0)
        try:
            self._client.report_serve_config(
                world=int(program.mesh.devices.size),
                serve_slots=int(program.spec.num_slots),
                prefill_chunk=int(program.prefill_chunk),
                kv_precision=str(program.spec.precision),
                max_seq=int(program.spec.max_seq),
                num_layers=int(program.spec.num_layers),
                kv_heads=int(program.spec.num_kv_heads),
                head_dim=int(program.spec.head_dim),
                prefix_pool_pages=int(program.spec.prefix_pool_pages),
                page_size=int(program.spec.page_size),
                prefix_hit_rate=float(hit_rate),
                spec_draft_len=int(program.spec_k),
                # -1 = "no draft observed yet": the optimizer prices
                # K>0 only from EVIDENCE (zero evidence = exactly 1.0x,
                # the prefix-discount discipline)
                spec_accept_rate=float(
                    self._spec_accepted_total / self._spec_drafted_total
                    if self._spec_drafted_total else -1.0),
                plan_id=plan_id, apply_failed=bool(apply_failed),
            )
        except Exception:  # noqa: BLE001 — a dead master must not
            # block serving
            logger.debug("serve config report failed", exc_info=True)

    def _poll_plan(self):
        if self._client is None or self._plan_poll <= 0 or not hasattr(
                self._client, "get_parallel_config"):
            return
        now = time.monotonic()
        if now - self._last_plan_poll < self._plan_poll:
            return
        self._last_plan_poll = now
        try:
            cfg = self._client.get_parallel_config()
        except Exception:  # noqa: BLE001 — master briefly away: retry
            # at the next poll cadence
            logger.debug("serve plan poll failed", exc_info=True)
            return
        plan_id = getattr(cfg, "plan_id", "") or ""
        slots = int(getattr(cfg, "serve_slots", 0) or 0)
        chunk = int(getattr(cfg, "serve_prefill_chunk", 0) or 0)
        # the pool and draft-length knobs' leave-unchanged sentinel is
        # -1 (0 is a real value: pool/spec off), unlike their
        # 0-sentinel siblings
        ppp = int(getattr(cfg, "serve_prefix_pool_pages", -1))
        sk = int(getattr(cfg, "serve_spec_draft_len", -1))
        if not plan_id or plan_id == self._seen_plan \
                or not (slots or chunk or ppp >= 0 or sk >= 0):
            return
        self._seen_plan = plan_id
        self.request_retune(serve_slots=slots or None,
                            prefill_chunk=chunk or None,
                            prefix_pool_pages=(ppp if ppp >= 0
                                               else None),
                            spec_draft_len=(sk if sk >= 0 else None),
                            plan_id=plan_id,
                            prewarm=bool(getattr(cfg, "prewarm", True)))

    def _touch(self):
        if self._client is None or not hasattr(self._client,
                                               "serve_touch"):
            return
        now = time.monotonic()
        if now - self._last_touch < 5.0:
            return
        self._last_touch = now
        try:
            self._client.serve_touch()
        except Exception:  # noqa: BLE001 — liveness is best-effort;
            # the lease-expiry scan is the backstop
            logger.debug("serve touch failed", exc_info=True)

    def serve(self, max_steps: int = 0, until_idle: bool = True):
        """Run the loop: admit → prefill tick → decode → lagged
        materialization, until the queue AND slots drain (or
        ``max_steps`` decode steps elapsed). Returns the completion
        records accumulated so far."""
        self._ensure_prepared()
        self._report_config()
        emit_event(EventKind.SERVE_START,
                   slots=self._engine.program.spec.num_slots,
                   prefill_chunk=self._engine.program.prefill_chunk,
                   kv_precision=self._engine.program.spec.precision,
                   spec_draft_len=self._engine.program.spec_k,
                   serve_seq=self._serve_seq)
        steps = 0
        idle_polls = 0
        loop_start = time.monotonic()
        self._ledger_mark = loop_start
        while True:
            # charge the elapsed interval to the ledger under the slot
            # states the PREVIOUS iteration left (the states that held
            # while its decode dispatch / materialization ran)
            self._charge_slots(time.monotonic())
            if self._resize_requested or self._retune_request is not None:
                self._drain_window()
                if self._resize_requested:
                    self._apply_resize()
                    self._report_config()
                if self._retune_request is not None:
                    self._apply_retune()
                # the drain + apply froze every slot: no decode or
                # prefill could run, whatever state the slots hold
                self._charge_slots(time.monotonic(),
                                   override="resize_frozen")
            self._poll_plan()
            self._admit()
            # the admission + prefill interval classifies by the state
            # that holds DURING it: a slot whose final chunk lands this
            # tick flips to decoding, and charging by the post-tick
            # state would fold every prefill second into decode
            pre_classes = (self._classify() if self._ledger_enabled
                           else None)
            self._prefill_tick()
            self._charge_slots(time.monotonic(), classes=pre_classes)
            self._touch()
            if not any(self._active_host):
                # nothing decoding: drain stragglers, then either a
                # fresh admission pass finds queued work or we are idle
                self._drain_window()
                if any(r is not None for r in self._slots):
                    continue  # admitted slots still prefilling
                if self._local_queue:
                    continue
                leased = self._lease(1)
                if leased:
                    self._local_queue.extend(leased)
                    continue
                idle_polls += 1
                if until_idle or (max_steps and steps >= max_steps) \
                        or idle_polls > 2:
                    break
                time.sleep(0.01)
                continue
            idle_polls = 0
            t0 = time.monotonic()
            if self._engine.program.verify is not None:
                # spec mode is SERIAL: the proposer needs current host
                # history before drafting, so the window (firsts-only
                # here) drains first and the verify step's host sync
                # is the price — amortized over up to K+1 tokens/slot
                self._drain_window()
                if not any(self._active_host):
                    continue  # the drain retired the last active slot
                self._spec_step()
            else:
                owners = {
                    i: r.request_id for i, r in enumerate(self._slots)
                    if r is not None and self._active_host[i]
                }
                with span(SpanName.SERVE_DECODE,
                          step=self.decode_steps):
                    next_tokens, _logits, self._engine.cache = (
                        self._engine.program.decode(
                            self._engine.params, self._engine.cache,
                            self._tokens, self._active))
                self._tokens = next_tokens
                self._window.append(
                    _InflightDecode(tokens=next_tokens, owners=owners))
                while len(self._window) > self._window_cap:
                    self._materialize_oldest()
            self._c_decode.inc()
            self.decode_steps += 1
            steps += 1
            self._h_step.observe(time.monotonic() - t0)
            if self._report_hook is not None:
                try:
                    self._report_hook.after_step(
                        self.decode_steps,
                        queue_len=len(self._local_queue),
                        slots=len(self._slots))
                except Exception:  # noqa: BLE001 — reporting must
                    # never take the decode loop down
                    logger.debug("serve runtime report hook failed",
                                 exc_info=True)
            if max_steps and steps >= max_steps:
                self._drain_window()
                break
        self._drain_window()
        now = time.monotonic()
        self._charge_slots(now)
        self._serve_wall += now - loop_start
        emit_event(EventKind.SERVE_END, decode_steps=self.decode_steps,
                   completed=len(self.completed),
                   slots=len(self._slots),
                   serve_seq=self._serve_seq,
                   slot_ledger={k: round(v, 6)
                                for k, v in self._ledger.items()},
                   slot_seconds=round(self._slot_seconds, 6),
                   serve_wall_s=round(self._serve_wall, 6),
                   prefix=self._engine.prefix_stats() or None,
                   spec=({"drafted": self._spec_drafted_total,
                          "accepted": self._spec_accepted_total,
                          "wasted": (self._spec_drafted_total
                                     - self._spec_accepted_total)}
                         if self._spec_drafted_total else None))
        if self._report_hook is not None:
            try:
                self._report_hook.flush(
                    queue_len=len(self._local_queue),
                    slots=len(self._slots))
            except Exception:  # noqa: BLE001 — best-effort final push
                logger.debug("serve runtime report flush failed",
                             exc_info=True)
        return list(self.completed)
