"""Python face of the C++ shared-memory batch ring.

Role parity: ``atorch/atorch/data/shm_context.py`` (shared-memory batch
transport between coworker preprocessing processes and trainers). Batches
are pytrees of numpy arrays; serialization is a tiny self-describing
header + raw array bytes (no pickle on the hot path).
"""

from __future__ import annotations

import ctypes
import errno
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from dlrover_tpu.common import tensor_codec
from dlrover_tpu.native import load_library

class RingClosed(Exception):
    """Producer closed the stream and every slot has been drained."""


class RingTimeout(Exception):
    pass


def _pack_batch(batch: Dict[str, np.ndarray]) -> bytes:
    """Shared framework codec (``common.tensor_codec``): json manifest +
    raw array bytes, no pickle on the hot path."""
    return tensor_codec.pack_frame({}, batch)


def _unpack_batch(buf: memoryview) -> Dict[str, np.ndarray]:
    # copy=True: the arrays must own their memory — the slot gets reused
    _meta, out = tensor_codec.unpack_frame(buf, copy=True)
    return out


class ShmBatchRing:
    """Create with ``owner=True`` in one process, ``attach`` elsewhere."""

    def __init__(self, name: str, slot_bytes: int = 1 << 22,
                 n_slots: int = 8, owner: bool = True):
        self._lib = load_library()
        self.name = name
        self.owner = owner
        if owner:
            handle = self._lib.shm_ring_create(
                name.encode(), slot_bytes, n_slots
            )
        else:
            handle = self._lib.shm_ring_attach(name.encode())
        if not handle:
            raise OSError(f"shm ring {name!r} unavailable "
                          f"(owner={owner})")
        self._handle = ctypes.c_void_p(handle)
        # the control block is authoritative (an attacher's guess at the
        # creator's slot size would livelock pop on a bigger payload)
        self._slot_bytes = int(self._lib.shm_ring_slot_size(self._handle))
        self._scratch = (ctypes.c_uint8 * self._slot_bytes)()
        self._pop_lock = threading.Lock()  # _scratch is shared per handle

    @classmethod
    def attach(cls, name: str, slot_bytes: int = 0) -> "ShmBatchRing":
        """slot size is read from the segment; the arg is ignored and kept
        for signature compatibility."""
        del slot_bytes
        return cls(name, owner=False)

    def put(self, batch: Dict[str, np.ndarray],
            timeout: float = 60.0) -> None:
        blob = _pack_batch(batch)
        if len(blob) > self._slot_bytes:
            raise ValueError(
                f"batch of {len(blob)} bytes exceeds slot size "
                f"{self._slot_bytes}"
            )
        # borrow the bytes object directly (the C side memcpys, never
        # mutates) — avoids a second full copy of the payload
        buf = ctypes.cast(ctypes.c_char_p(blob),
                          ctypes.POINTER(ctypes.c_uint8))
        rc = self._lib.shm_ring_push(
            self._handle, buf, len(blob), int(timeout * 1000)
        )
        if rc == errno.ETIMEDOUT:
            raise RingTimeout(f"put timed out after {timeout}s")
        if rc == errno.EPIPE:
            raise RingClosed("ring closed")
        if rc:
            raise OSError(f"shm_ring_push failed: errno {rc}")

    def get(self, timeout: float = 60.0) -> Dict[str, np.ndarray]:
        with self._pop_lock:
            n = self._lib.shm_ring_pop(
                self._handle, self._scratch, self._slot_bytes,
                int(timeout * 1000),
            )
            if n == -errno.ETIMEDOUT:
                raise RingTimeout(f"get timed out after {timeout}s")
            if n == -errno.EPIPE:
                raise RingClosed("ring closed and drained")
            if n < 0:
                raise OSError(f"shm_ring_pop failed: errno {-n}")
            return _unpack_batch(memoryview(self._scratch)[:n])

    def qsize(self) -> int:
        return max(0, self._lib.shm_ring_size(self._handle))

    def close(self) -> None:
        """Signal end-of-stream (consumers drain, then see RingClosed)."""
        if self._handle:
            self._lib.shm_ring_close(self._handle)

    def free(self) -> None:
        """Unmap (and unlink, if owner) the segment."""
        if self._handle:
            self._lib.shm_ring_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        self.free()
