"""Native (C++) runtime pieces + the load/build bridge.

Role parity: tfplus's custom-op scaffold (``tfplus/tfplus/cc/demo.{h,cc}``,
``tfplus/tfplus/python/demo.py:10`` ``_load_library`` bridge) — but with
real kernels behind it: the shared-memory batch ring
(``native/src/shm_ring.cc``, the atorch ``shm_context`` data path) and
host-side batch-prep ops (``native/src/host_ops.cc``).

The library is built on demand with a plain ``g++`` invocation (no
pybind11 in this environment; the ABI is a C API consumed over ctypes).
``CMakeLists.txt`` provides the standalone build scaffold.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_BUILD_ERROR: Optional[str] = None

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "lib")
_LIB_PATH = os.path.join(_LIB_DIR, "libdlrover_tpu_native.so")
_SOURCES = ("shm_ring.cc", "host_ops.cc")


def _build() -> str:
    os.makedirs(_LIB_DIR, exist_ok=True)
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if (os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) >= newest_src):
        return _LIB_PATH
    # compile to a private temp path, then atomically rename: a second
    # cold-starting process must never dlopen a half-written .so
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-std=c++17", "-O3", "-shared", "-fPIC",
        "-Wall", "-Wextra",
        *srcs,
        "-o", tmp_path,
        "-lpthread", "-lrt",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp_path, _LIB_PATH)
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
    return _LIB_PATH


def load_library() -> ctypes.CDLL:
    """Build (if stale) and load the native library; raises RuntimeError
    with the compiler output when the toolchain is unavailable/broken."""
    global _LIB, _BUILD_ERROR
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        if _BUILD_ERROR is not None:
            raise RuntimeError(_BUILD_ERROR)
        try:
            path = _build()
            lib = ctypes.CDLL(path)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _BUILD_ERROR = f"native library unavailable: {detail}"
            raise RuntimeError(_BUILD_ERROR) from e
        _declare_signatures(lib)
        _LIB = lib
        return lib


def native_available() -> bool:
    try:
        load_library()
        return True
    except RuntimeError:
        return False


def _declare_signatures(lib: ctypes.CDLL):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.shm_ring_create.restype = ctypes.c_void_p
    lib.shm_ring_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64
    ]
    lib.shm_ring_attach.restype = ctypes.c_void_p
    lib.shm_ring_attach.argtypes = [ctypes.c_char_p]
    lib.shm_ring_push.restype = ctypes.c_int
    lib.shm_ring_push.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint64, ctypes.c_long
    ]
    lib.shm_ring_pop.restype = ctypes.c_long
    lib.shm_ring_pop.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint64, ctypes.c_long
    ]
    lib.shm_ring_size.restype = ctypes.c_long
    lib.shm_ring_size.argtypes = [ctypes.c_void_p]
    lib.shm_ring_slot_size.restype = ctypes.c_long
    lib.shm_ring_slot_size.argtypes = [ctypes.c_void_p]
    lib.shm_ring_close.restype = None
    lib.shm_ring_close.argtypes = [ctypes.c_void_p]
    lib.shm_ring_free.restype = None
    lib.shm_ring_free.argtypes = [ctypes.c_void_p]

    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.pack_sequences.restype = None
    lib.pack_sequences.argtypes = [
        i32p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        i32p, i32p,
    ]
    lib.shuffle_indices.restype = None
    lib.shuffle_indices.argtypes = [i64p, ctypes.c_int64, ctypes.c_uint64]
    lib.shift_labels.restype = None
    lib.shift_labels.argtypes = [
        i32p, i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, i32p,
    ]
