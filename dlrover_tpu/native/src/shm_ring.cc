// Shared-memory ring buffer for coworker-style batch transport.
//
// Role parity: atorch's shared-memory data path
// (atorch/atorch/data/shm_context.py:20-682 + shm_dataloader.py:38-220):
// CPU preprocessing processes produce ready batches into shared memory and
// trainer processes consume them without pickling through pipes. The
// reference implements this in Python over multiprocessing.shared_memory;
// here the hot path (slot bookkeeping, blocking, copies) is C++ and the
// Python side only moves numpy views (see native/shm_ring.py).
//
// Design: one POSIX shm segment = control block + N fixed-size slots.
// MPMC-safe via a process-shared pthread mutex + two condvars (not-full /
// not-empty); producers and consumers may be different processes. All
// blocking calls take a timeout so an elastic restart never wedges on a
// dead peer.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x444c52544f525251ull;  // "DLRTORRQ"

struct ControlBlock {
  uint64_t magic;
  uint64_t slot_size;   // payload capacity per slot
  uint64_t n_slots;
  uint64_t head;        // next slot to write
  uint64_t tail;        // next slot to read
  uint64_t count;       // filled slots
  uint64_t closed;      // producer signalled end-of-stream
  pthread_mutex_t mutex;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
};

struct SlotHeader {
  uint64_t len;
};

struct Ring {
  ControlBlock* ctrl;
  uint8_t* slots;       // n_slots * (sizeof(SlotHeader) + slot_size)
  size_t map_size;
  bool owner;
  char name[256];
};

size_t slot_stride(const ControlBlock* c) {
  return sizeof(SlotHeader) + c->slot_size;
}

uint8_t* slot_at(Ring* r, uint64_t idx) {
  return r->slots + idx * slot_stride(r->ctrl);
}

void deadline_after_ms(timespec* ts, long timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle, or null on failure (errno holds the cause).
void* shm_ring_create(const char* name, uint64_t slot_size,
                      uint64_t n_slots) {
  size_t map_size =
      sizeof(ControlBlock) + n_slots * (sizeof(SlotHeader) + slot_size);
  shm_unlink(name);  // stale segment from a crashed predecessor
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }

  auto* ctrl = static_cast<ControlBlock*>(mem);
  std::memset(ctrl, 0, sizeof(ControlBlock));
  ctrl->slot_size = slot_size;
  ctrl->n_slots = n_slots;

  pthread_mutexattr_t mattr;
  pthread_mutexattr_init(&mattr);
  pthread_mutexattr_setpshared(&mattr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&mattr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&ctrl->mutex, &mattr);
  pthread_mutexattr_destroy(&mattr);

  pthread_condattr_t cattr;
  pthread_condattr_init(&cattr);
  pthread_condattr_setpshared(&cattr, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&ctrl->not_full, &cattr);
  pthread_cond_init(&ctrl->not_empty, &cattr);
  pthread_condattr_destroy(&cattr);

  ctrl->magic = kMagic;

  auto* ring = new Ring();
  ring->ctrl = ctrl;
  ring->slots = static_cast<uint8_t*>(mem) + sizeof(ControlBlock);
  ring->map_size = map_size;
  ring->owner = true;
  std::strncpy(ring->name, name, sizeof(ring->name) - 1);
  return ring;
}

void* shm_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* ctrl = static_cast<ControlBlock*>(mem);
  if (ctrl->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    errno = EINVAL;
    return nullptr;
  }
  auto* ring = new Ring();
  ring->ctrl = ctrl;
  ring->slots = static_cast<uint8_t*>(mem) + sizeof(ControlBlock);
  ring->map_size = static_cast<size_t>(st.st_size);
  ring->owner = false;
  std::strncpy(ring->name, name, sizeof(ring->name) - 1);
  return ring;
}

// Lock helper tolerating a peer that died while holding the mutex.
static int lock_robust(ControlBlock* c) {
  int rc = pthread_mutex_lock(&c->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&c->mutex);
    rc = 0;
  }
  return rc;
}

// 0 ok; ETIMEDOUT on timeout; EMSGSIZE if len > slot_size; EPIPE if closed.
int shm_ring_push(void* handle, const uint8_t* data, uint64_t len,
                  long timeout_ms) {
  auto* r = static_cast<Ring*>(handle);
  ControlBlock* c = r->ctrl;
  if (len > c->slot_size) return EMSGSIZE;
  if (lock_robust(c) != 0) return EINVAL;
  timespec deadline;
  deadline_after_ms(&deadline, timeout_ms);
  while (c->count == c->n_slots && !c->closed) {
    int rc = pthread_cond_timedwait(&c->not_full, &c->mutex, &deadline);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&c->mutex);
      return ETIMEDOUT;
    }
    // a peer died holding the mutex: mark it consistent or the mutex is
    // permanently unrecoverable for every survivor
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&c->mutex);
  }
  if (c->closed) {
    pthread_mutex_unlock(&c->mutex);
    return EPIPE;
  }
  uint8_t* slot = slot_at(r, c->head % c->n_slots);
  reinterpret_cast<SlotHeader*>(slot)->len = len;
  std::memcpy(slot + sizeof(SlotHeader), data, len);
  c->head++;
  c->count++;
  pthread_cond_signal(&c->not_empty);
  pthread_mutex_unlock(&c->mutex);
  return 0;
}

// Returns payload length popped into out; -ETIMEDOUT / -EPIPE (closed and
// drained) / -EMSGSIZE (cap too small) as negatives.
long shm_ring_pop(void* handle, uint8_t* out, uint64_t cap,
                  long timeout_ms) {
  auto* r = static_cast<Ring*>(handle);
  ControlBlock* c = r->ctrl;
  if (lock_robust(c) != 0) return -EINVAL;
  timespec deadline;
  deadline_after_ms(&deadline, timeout_ms);
  while (c->count == 0 && !c->closed) {
    int rc = pthread_cond_timedwait(&c->not_empty, &c->mutex, &deadline);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&c->mutex);
      return -ETIMEDOUT;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&c->mutex);
  }
  if (c->count == 0 && c->closed) {
    pthread_mutex_unlock(&c->mutex);
    return -EPIPE;
  }
  uint8_t* slot = slot_at(r, c->tail % c->n_slots);
  uint64_t len = reinterpret_cast<SlotHeader*>(slot)->len;
  if (len > cap) {
    pthread_mutex_unlock(&c->mutex);
    return -EMSGSIZE;
  }
  std::memcpy(out, slot + sizeof(SlotHeader), len);
  c->tail++;
  c->count--;
  pthread_cond_signal(&c->not_full);
  pthread_mutex_unlock(&c->mutex);
  return static_cast<long>(len);
}

long shm_ring_slot_size(void* handle) {
  return static_cast<long>(static_cast<Ring*>(handle)->ctrl->slot_size);
}

long shm_ring_size(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  if (lock_robust(r->ctrl) != 0) return -EINVAL;
  long n = static_cast<long>(r->ctrl->count);
  pthread_mutex_unlock(&r->ctrl->mutex);
  return n;
}

// Signal end-of-stream: consumers drain remaining slots then get EPIPE.
void shm_ring_close(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  if (lock_robust(r->ctrl) == 0) {
    r->ctrl->closed = 1;
    pthread_cond_broadcast(&r->ctrl->not_empty);
    pthread_cond_broadcast(&r->ctrl->not_full);
    pthread_mutex_unlock(&r->ctrl->mutex);
  }
}

void shm_ring_free(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  bool owner = r->owner;
  char name[256];
  std::strncpy(name, r->name, sizeof(name));
  munmap(static_cast<void*>(r->ctrl), r->map_size);
  if (owner) shm_unlink(name);
  delete r;
}

}  // extern "C"
