// Host-side data-prep kernels (CPU, feeding the TPU input pipeline).
//
// Role parity: the tfplus custom-op scaffold (tfplus/tfplus/cc/demo.{h,cc}
// + BUILD) whose job is "a real C++ kernel behind a Python loader", and
// the CPU side of atorch's coworker preprocessing (atorch/atorch/data/).
// These run in producer processes so the trainer never burns Python time
// packing batches.

#include <algorithm>
#include <cstdint>
#include <cstring>

extern "C" {

// Pack ragged token sequences into a fixed [n_seqs, max_len] batch.
//   tokens  : concatenated token ids
//   offsets : n_seqs+1 prefix offsets into tokens
//   out_ids : [n_seqs, max_len] padded with pad_id (truncates long seqs)
//   out_mask: [n_seqs, max_len] 1 where a real token lives, else 0
void pack_sequences(const int32_t* tokens, const int64_t* offsets,
                    int64_t n_seqs, int64_t max_len, int32_t pad_id,
                    int32_t* out_ids, int32_t* out_mask) {
  for (int64_t i = 0; i < n_seqs; ++i) {
    const int64_t start = offsets[i];
    const int64_t len =
        std::min<int64_t>(offsets[i + 1] - start, max_len);
    int32_t* row = out_ids + i * max_len;
    int32_t* mask = out_mask + i * max_len;
    std::memcpy(row, tokens + start, len * sizeof(int32_t));
    for (int64_t j = 0; j < len; ++j) mask[j] = 1;
    for (int64_t j = len; j < max_len; ++j) {
      row[j] = pad_id;
      mask[j] = 0;
    }
  }
}

// Deterministic in-place Fisher-Yates shuffle of an index array using
// splitmix64 — the record-shuffle primitive for dynamic data sharding
// (each worker shuffles within its received shard, seeded by epoch).
static inline uint64_t splitmix64(uint64_t* state) {
  uint64_t z = (*state += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

void shuffle_indices(int64_t* indices, int64_t n, uint64_t seed) {
  uint64_t state = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    const int64_t j =
        static_cast<int64_t>(splitmix64(&state) % (uint64_t)(i + 1));
    std::swap(indices[i], indices[j]);
  }
}

// Causal-LM label shift: labels[i, :-1] = ids[i, 1:], labels[i, -1] and
// every padded position become ignore_id (the -100 HF convention the
// loss masks on, models/losses.py).
void shift_labels(const int32_t* ids, const int32_t* mask, int64_t n_rows,
                  int64_t row_len, int32_t ignore_id, int32_t* out_labels) {
  for (int64_t i = 0; i < n_rows; ++i) {
    const int32_t* row = ids + i * row_len;
    const int32_t* m = mask + i * row_len;
    int32_t* out = out_labels + i * row_len;
    for (int64_t j = 0; j + 1 < row_len; ++j) {
      out[j] = m[j + 1] ? row[j + 1] : ignore_id;
    }
    out[row_len - 1] = ignore_id;
  }
}

}  // extern "C"
