"""numpy-facing wrappers for the C++ host ops, with pure-numpy fallbacks.

The native path (``native/src/host_ops.cc``) is used when the toolchain
is available; the fallback keeps the package importable anywhere (same
contract as tfplus's optional ``_demo.so``).
"""

from __future__ import annotations

import ctypes
from typing import Tuple

import numpy as np

from dlrover_tpu.native import load_library, native_available


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def pack_sequences(tokens: np.ndarray, offsets: np.ndarray, max_len: int,
                   pad_id: int = 0,
                   use_native: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged [sum(lens)] tokens + [N+1] offsets -> ([N, max_len] ids,
    [N, max_len] mask); long sequences truncate, short ones pad."""
    tokens = np.ascontiguousarray(tokens, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = len(offsets) - 1
    ids = np.empty((n, max_len), np.int32)
    mask = np.empty((n, max_len), np.int32)
    if use_native and native_available():
        lib = load_library()
        lib.pack_sequences(
            _as_ptr(tokens, ctypes.c_int32), _as_ptr(offsets, ctypes.c_int64),
            n, max_len, pad_id,
            _as_ptr(ids, ctypes.c_int32), _as_ptr(mask, ctypes.c_int32),
        )
        return ids, mask
    for i in range(n):
        seq = tokens[offsets[i]:offsets[i + 1]][:max_len]
        ids[i, :len(seq)] = seq
        ids[i, len(seq):] = pad_id
        mask[i, :len(seq)] = 1
        mask[i, len(seq):] = 0
    return ids, mask


def shuffle_indices(n: int, seed: int,
                    use_native: bool = True) -> np.ndarray:
    """Deterministic permutation of arange(n) (splitmix64 Fisher-Yates)."""
    indices = np.arange(n, dtype=np.int64)
    if use_native and native_available():
        lib = load_library()
        lib.shuffle_indices(_as_ptr(indices, ctypes.c_int64), n,
                            ctypes.c_uint64(seed))
        return indices
    # fallback reproduces the native splitmix64 stream exactly
    state = seed & 0xFFFFFFFFFFFFFFFF

    def next_u64():
        nonlocal state
        state = (state + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return z ^ (z >> 31)

    for i in range(n - 1, 0, -1):
        j = next_u64() % (i + 1)
        indices[i], indices[j] = indices[j], indices[i]
    return indices


def shift_labels(ids: np.ndarray, mask: np.ndarray, ignore_id: int = -100,
                 use_native: bool = True) -> np.ndarray:
    """Causal-LM next-token labels; padded positions get ignore_id."""
    ids = np.ascontiguousarray(ids, np.int32)
    mask = np.ascontiguousarray(mask, np.int32)
    n, s = ids.shape
    labels = np.empty((n, s), np.int32)
    if use_native and native_available():
        lib = load_library()
        lib.shift_labels(
            _as_ptr(ids, ctypes.c_int32), _as_ptr(mask, ctypes.c_int32),
            n, s, ignore_id, _as_ptr(labels, ctypes.c_int32),
        )
        return labels
    labels[:, :-1] = np.where(mask[:, 1:] == 1, ids[:, 1:], ignore_id)
    labels[:, -1] = ignore_id
    return labels
