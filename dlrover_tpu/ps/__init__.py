"""Asynchronous parameter-server execution path.

Role parity: the reference's PS *distribution strategy* — scheduling and
membership live in the master (``dlrover/python/master/node/ps.py``,
``elastic_training/elastic_ps.py``), while the execution engine there is
TensorFlow's parameter-server runtime driven through the estimator trainer
(``dlrover/trainer/tensorflow/``, DeepRec CPU PS jobs in
``docs/blogs/deeprec_autoscale_cn.md``). We do not wrap TF; this package is
the TPU-framework-native execution engine for that strategy:

- ``ps.server``  — a PS shard process: host-memory parameter store with
  numpy-native optimizers applied on push (the PS owns optimizer state,
  exactly like TF's PS applies updates server-side).
- ``ps.client``  — worker-side cluster view: discovers PS shards through the
  master, partitions parameters across shards (size-balanced), pulls and
  pushes tensors over a binary gRPC framing.
- ``ps.trainer`` — the async training loop: grads computed with jax (jit on
  the accelerator), pushed asynchronously; elastic PS membership changes are
  picked up through the master's cluster-version handshake.

Sparse/CPU recommendation models (DeepFM et al.) are the intended workload,
mirroring the reference's DeepRec positioning; dense LLM training on TPU
uses the synchronous GSPMD path in ``dlrover_tpu.parallel`` instead.
"""

from dlrover_tpu.ps.client import PsClusterClient, partition_params
from dlrover_tpu.ps.server import PsShardServer, start_ps_shard
from dlrover_tpu.ps.trainer import AsyncPsTrainer

__all__ = [
    "PsClusterClient",
    "partition_params",
    "PsShardServer",
    "start_ps_shard",
    "AsyncPsTrainer",
]
