"""Binary tensor framing for the PS data plane.

The control plane stays on the JSON dataclass codec (``common.serialize``);
parameter pull/push moves megabytes of tensors per call, so it uses the
shared binary frame (``common.tensor_codec`` — same codec as the shm data
ring, one implementation to keep bug-compatible).
"""

from __future__ import annotations

from dlrover_tpu.common.tensor_codec import pack_frame, unpack_frame

__all__ = ["pack_frame", "unpack_frame", "identity"]


def identity(b: bytes) -> bytes:
    """Serializer for the raw-bytes gRPC method."""
    return b
