"""Binary tensor framing for the PS data plane.

The control plane stays on the JSON dataclass codec (``common.serialize``);
parameter pull/push moves megabytes of tensors per call, so it gets a raw
binary frame instead: a JSON header (op, metadata, tensor manifest) followed
by the concatenated array buffers. No base64, no copies beyond the single
``b"".join``.

Frame layout::

    [4-byte big-endian header length][header JSON][buf0][buf1]...

Header::

    {"meta": {...}, "tensors": [{"name","dtype","shape","nbytes"}, ...]}
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Tuple

import numpy as np

_LEN = struct.Struct(">I")


def pack_frame(meta: Dict[str, Any],
               tensors: Dict[str, np.ndarray] | None = None) -> bytes:
    tensors = tensors or {}
    manifest = []
    bufs = []
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        manifest.append({
            "name": name,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "nbytes": arr.nbytes,
        })
        bufs.append(arr.tobytes())
    header = json.dumps({"meta": meta, "tensors": manifest}).encode()
    return b"".join([_LEN.pack(len(header)), header] + bufs)


def unpack_frame(frame: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    (hlen,) = _LEN.unpack_from(frame, 0)
    header = json.loads(frame[4:4 + hlen].decode())
    tensors: Dict[str, np.ndarray] = {}
    offset = 4 + hlen
    view = memoryview(frame)
    for entry in header["tensors"]:
        n = entry["nbytes"]
        arr = np.frombuffer(
            view[offset:offset + n], dtype=np.dtype(entry["dtype"])
        ).reshape(entry["shape"])
        tensors[entry["name"]] = arr
        offset += n
    return header["meta"], tensors


def identity(b: bytes) -> bytes:
    """Serializer for the raw-bytes gRPC method."""
    return b
