"""PS shard server: host-memory parameter store with server-side updates.

Role parity: the parameter-server side of the reference's PS strategy. There
the PS is a TensorFlow server applying optimizer updates in its own process
(DeepRec CPU PS jobs, ``docs/blogs/deeprec_autoscale_cn.md``); the DLRover
master schedules and migrates those processes
(``dlrover/python/master/node/ps.py:198,315``). Here the PS shard is a small
gRPC process holding a dict of numpy parameters and per-parameter optimizer
slots, applying updates on ``push`` — server-side application is what makes
the strategy *asynchronous*: workers never wait for each other, only for
their own push/pull round-trips.

Updates run in numpy (C-level, no GIL-bound Python loops over elements),
which is the honest host-side analogue of TF's C++ apply-ops. Grad staleness
is inherent to async PS: a worker's push lands on parameters other workers
have advanced since its pull. The pull-compute-push cadence bounds it to one
compute duration; the version counter in pull/push responses exposes it for
monitoring.

Checkpoint/restore is a single ``.npz`` per shard, so a migrated PS (master
scale event) restores its slice and bumps the cluster version; workers
re-resolve addresses and re-pull (``tensorflow_failover.py:33-144`` parity).
"""

from __future__ import annotations

import os
import threading
from concurrent import futures
from typing import Dict, Optional, Tuple

import grpc
import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.ps import wire

logger = get_logger("ps.server")

PS_SERVICE = "dlrover_tpu.PS"
PS_METHOD = f"/{PS_SERVICE}/call"


# ---------------------------------------------------------------------------
# numpy optimizers (PS-side slots)
# ---------------------------------------------------------------------------

class _NpOptimizer:
    """Server-side optimizer: one slot-dict per parameter."""

    def __init__(self, spec: str):
        # spec: "sgd:0.1" | "momentum:0.1:0.9" | "adagrad:0.05" | "adam:1e-3"
        parts = spec.split(":")
        self.kind = parts[0]
        self.lr = float(parts[1]) if len(parts) > 1 else 0.01
        self.extra = [float(p) for p in parts[2:]]
        if self.kind not in ("sgd", "momentum", "adagrad", "adam"):
            raise ValueError(f"unknown PS optimizer {self.kind!r}")

    def init_slots(self, param: np.ndarray) -> Dict[str, np.ndarray]:
        if self.kind == "sgd":
            return {}
        if self.kind == "momentum":
            return {"m": np.zeros_like(param)}
        if self.kind == "adagrad":
            return {"acc": np.full_like(param, 0.1)}
        return {"m": np.zeros_like(param), "v": np.zeros_like(param),
                "t": np.zeros((), np.int64)}

    def apply(self, param: np.ndarray, grad: np.ndarray,
              slots: Dict[str, np.ndarray]) -> None:
        grad = grad.astype(param.dtype, copy=False)
        if self.kind == "sgd":
            param -= self.lr * grad
        elif self.kind == "momentum":
            mu = self.extra[0] if self.extra else 0.9
            slots["m"] *= mu
            slots["m"] += grad
            param -= self.lr * slots["m"]
        elif self.kind == "adagrad":
            slots["acc"] += grad * grad
            param -= self.lr * grad / np.sqrt(slots["acc"])
        else:  # adam
            b1 = self.extra[0] if len(self.extra) > 0 else 0.9
            b2 = self.extra[1] if len(self.extra) > 1 else 0.999
            slots["t"] += 1
            t = int(slots["t"])
            slots["m"] *= b1
            slots["m"] += (1 - b1) * grad
            slots["v"] *= b2
            slots["v"] += (1 - b2) * grad * grad
            mhat = slots["m"] / (1 - b1 ** t)
            vhat = slots["v"] / (1 - b2 ** t)
            param -= self.lr * mhat / (np.sqrt(vhat) + 1e-8)


# ---------------------------------------------------------------------------
# shard server
# ---------------------------------------------------------------------------

class PsShardServer:
    """One PS shard: params + optimizer slots + a raw-bytes gRPC service."""

    def __init__(self, shard_id: int, optimizer: str = "adagrad:0.05",
                 checkpoint_dir: Optional[str] = None):
        self.shard_id = shard_id
        self._opt = _NpOptimizer(optimizer)
        self._ckpt_dir = checkpoint_dir
        self._lock = threading.Lock()
        self._params: Dict[str, np.ndarray] = {}
        self._slots: Dict[str, Dict[str, np.ndarray]] = {}
        self._version = 0  # total applied pushes (staleness reference)
        self._server: Optional[grpc.Server] = None
        self.addr: Optional[str] = None

    # -- rpc entry ---------------------------------------------------------

    def call(self, request: bytes, context=None) -> bytes:
        try:
            return self._dispatch(request)
        except Exception as exc:  # keep the {'ok': False} error contract
            logger.exception("PS shard %d op failed", self.shard_id)
            return wire.pack_frame({"ok": False, "error": repr(exc)})

    def _dispatch(self, request: bytes) -> bytes:
        meta, tensors = wire.unpack_frame(request)
        op = meta.get("op")
        if op == "init":
            return self._do_init(meta, tensors)
        if op == "pull":
            return self._do_pull(meta)
        if op == "push":
            return self._do_push(meta, tensors)
        if op == "checkpoint":
            return self._do_checkpoint(meta)
        if op == "restore":
            return self._do_restore(meta)
        if op == "stats":
            with self._lock:
                return wire.pack_frame({
                    "ok": True, "version": self._version,
                    "num_params": len(self._params),
                    "bytes": int(sum(p.nbytes for p in self._params.values())),
                })
        return wire.pack_frame({"ok": False, "error": f"unknown op {op!r}"})

    # -- ops ---------------------------------------------------------------

    def _do_init(self, meta, tensors) -> bytes:
        """Create parameters that don't exist yet (idempotent: a worker
        racing another worker's init, or re-initing after PS restore, is a
        no-op for existing keys)."""
        created = []
        with self._lock:
            for name, arr in tensors.items():
                if name not in self._params:
                    self._params[name] = np.array(arr, copy=True)
                    self._slots[name] = self._opt.init_slots(self._params[name])
                    created.append(name)
            version = self._version
        return wire.pack_frame({"ok": True, "created": created,
                                "version": version})

    def _do_pull(self, meta) -> bytes:
        names = meta.get("names")
        with self._lock:
            if names is None:
                names = list(self._params)
            missing = [n for n in names if n not in self._params]
            if missing:
                return wire.pack_frame(
                    {"ok": False, "error": "missing", "missing": missing})
            out = {n: self._params[n].copy() for n in names}
            version = self._version
        return wire.pack_frame({"ok": True, "version": version}, out)

    def _do_push(self, meta, tensors) -> bytes:
        with self._lock:
            missing = [n for n in tensors if n not in self._params]
            if missing:
                return wire.pack_frame(
                    {"ok": False, "error": "missing", "missing": missing})
            for name, grad in tensors.items():
                self._opt.apply(self._params[name], grad, self._slots[name])
            self._version += 1
            version = self._version
        return wire.pack_frame({"ok": True, "version": version})

    def _ckpt_path(self, directory: Optional[str]) -> str:
        d = directory or self._ckpt_dir
        if not d:
            raise ValueError("no checkpoint dir configured")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"ps-shard-{self.shard_id}.npz")

    def _do_checkpoint(self, meta) -> bytes:
        path = self._ckpt_path(meta.get("dir"))
        with self._lock:
            payload = {f"p/{n}": a for n, a in self._params.items()}
            for n, slots in self._slots.items():
                for sname, sval in slots.items():
                    payload[f"s/{n}/{sname}"] = sval
            payload["__version__"] = np.asarray(self._version, np.int64)
            tmp = path + ".tmp.npz"  # .npz suffix keeps savez from renaming
            np.savez(tmp, **payload)
            os.replace(tmp, path)
        return wire.pack_frame({"ok": True, "path": path})

    def _do_restore(self, meta) -> bytes:
        path = self._ckpt_path(meta.get("dir"))
        if not os.path.exists(path):
            return wire.pack_frame({"ok": False, "error": "no checkpoint"})
        with self._lock:
            self._params.clear()
            self._slots.clear()
            with np.load(path) as data:
                for key in data.files:
                    if key == "__version__":
                        self._version = int(data[key])
                    elif key.startswith("p/"):
                        self._params[key[2:]] = np.array(data[key])
                for key in data.files:
                    if key.startswith("s/"):
                        # slot names ("m","v","t","acc") never contain "/",
                        # so rsplit keeps param names with "/" intact
                        name, sname = key[2:].rsplit("/", 1)
                        self._slots.setdefault(name, {})[sname] = \
                            np.array(data[key])
            # params restored without slots (optimizer change): re-init
            for name in self._params:
                if name not in self._slots:
                    self._slots[name] = self._opt.init_slots(self._params[name])
            version = self._version
            num_params = len(self._params)
        return wire.pack_frame({"ok": True, "version": version,
                                "num_params": num_params})

    # -- lifecycle ---------------------------------------------------------

    def start(self, port: int = 0, host: str = "127.0.0.1") -> str:
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=16),
                             options=[
            ("grpc.max_send_message_length", 1024 * 1024 * 1024),
            ("grpc.max_receive_message_length", 1024 * 1024 * 1024),
        ])
        shard = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method != PS_METHOD:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    shard.call,
                    request_deserializer=wire.identity,
                    response_serializer=wire.identity,
                )

        server.add_generic_rpc_handlers((_Handler(),))
        bound = server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise RuntimeError("cannot bind PS shard port")
        server.start()
        self._server = server
        self.addr = f"{host}:{bound}"
        logger.info("PS shard %d serving at %s", self.shard_id, self.addr)
        return self.addr

    def stop(self):
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None


def start_ps_shard(shard_id: int, master_client=None,
                   optimizer: str = "adagrad:0.05",
                   checkpoint_dir: Optional[str] = None,
                   restore: bool = False,
                   num_shards: Optional[int] = None,
                   port: int = 0) -> PsShardServer:
    """Start a shard and register its address with the master's KV store so
    workers can discover it (``ps/addr/{shard_id}``). A replacement shard for
    the same id (PS migration) overwrites the key; the migration driver then
    bumps the global cluster version and workers re-resolve. With
    ``restore=True`` the shard reloads its slice from ``checkpoint_dir``
    before serving (the migration path)."""
    shard = PsShardServer(shard_id, optimizer=optimizer,
                          checkpoint_dir=checkpoint_dir)
    if restore:
        meta, _ = wire.unpack_frame(shard.call(wire.pack_frame(
            {"op": "restore"})))
        if not meta.get("ok"):
            raise RuntimeError(f"PS shard {shard_id} restore failed: {meta}")
    addr = shard.start(port=port)
    if master_client is not None:
        if num_shards is not None:
            # read the PREVIOUS generation's count before overwriting it:
            # it bounds the stale-key sweep even when the old key range
            # has gaps (a shard that never registered must not shield the
            # stale keys behind it from clearing)
            prev = master_client.kv_store_get("ps/count")
            try:
                prev_count = int(prev) if prev else 0
            except ValueError:
                prev_count = 0
            # announce cluster size BEFORE the addr key: discovery keyed on
            # ps/count must never observe addr keys without the count, or a
            # worker racing registration adopts a partial list and computes
            # a divergent placement
            master_client.kv_store_set("ps/count", str(num_shards))
            # two complementary defenses against stale addr keys:
            # (1) the value carries its generation (the announced count),
            #     so discovery rejects keys a DIFFERENT-sized generation
            #     wrote even if clearing races a straggler writer;
            # (2) keys beyond the announced count — swept up to the
            #     previous generation's count regardless of gaps — are
            #     cleared, covering resize-back-to-a-previous-size where
            #     the count tag alone cannot distinguish generations.
            # Residual: a still-running straggler shard of a SAME-sized
            # previous generation re-registering late — the migration
            # driver's contract is to stop old shards before starting
            # new ones (the version bump is the sync point).
            for i in range(num_shards, max(prev_count, num_shards)):
                master_client.kv_store_set(f"ps/addr/{i}", "")
            master_client.kv_store_set(f"ps/addr/{shard_id}",
                                       f"{addr}|{num_shards}")
        else:
            master_client.kv_store_set(f"ps/addr/{shard_id}", addr)
    return shard
