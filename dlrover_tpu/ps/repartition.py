"""Offline PS checkpoint repartitioning for cluster resizes.

Role parity: the reference resizes PS clusters through checkpoint +
restart (``dlrover/python/master/node/ps.py`` scale-up/down drives a new
PS cluster version; TF restores variables onto the new partitioning).
Here the migration driver runs this utility between stopping the old
shards and starting the new ones:

    repartition_checkpoint(ckpt_dir, old_n, new_n)

It merges every shard's parameter slice + optimizer slots, recomputes
the deterministic size-balanced placement for ``new_n`` shards (the same
``partition_params`` every worker uses), and rewrites the per-shard
``.npz`` files. New shards then ``restore=True`` their slice; workers
detect the version bump, see the resized address list, drop their stale
placement, and recompute it against the restored cluster.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.ps.client import partition_params

logger = get_logger("ps.repartition")


def _shard_path(directory: str, shard_id: int) -> str:
    return os.path.join(directory, f"ps-shard-{shard_id}.npz")


def repartition_checkpoint(directory: str, old_num_shards: int,
                           new_num_shards: int) -> Dict[str, int]:
    """Rewrite per-shard checkpoint files for a new shard count.

    Returns the new name -> shard assignment. Atomic per file (tmp +
    rename); old files beyond the new count are removed last, so a crash
    mid-way leaves a restorable superset."""
    params: Dict[str, np.ndarray] = {}
    slots: Dict[str, Dict[str, np.ndarray]] = {}
    version = 0

    def ingest(path, tolerate_torn=False):
        nonlocal version
        # the whole read sits in the try: a torn tmp can fail at open OR
        # at member decode (zip directory persisted, data blocks not)
        try:
            with np.load(path) as data:
                staged = []
                for key in data.files:
                    staged.append((key, np.array(data[key])))
        except Exception:  # noqa: BLE001 — torn write from a killed run
            if tolerate_torn:
                # safe to skip: tmp writes complete strictly BEFORE any
                # rename in a run, so a torn tmp's source data is still
                # in a canonical file or another (complete) tmp
                logger.warning("skipping unreadable leftover %s", path)
                return
            raise
        for key, arr in staged:
            if key == "__version__":
                version = max(version, int(arr))
            elif key.startswith("p/"):
                params.setdefault(key[2:], arr)
            elif key.startswith("s/"):
                name, sname = key[2:].rsplit("/", 1)
                slots.setdefault(name, {}).setdefault(sname, arr)

    found_any = False
    for i in range(old_num_shards):
        path = _shard_path(directory, i)
        if not os.path.exists(path):
            if i >= new_num_shards:
                # a crashed downsize rerun only ever REMOVES ids in
                # [new, old) — a missing file there is the benign
                # mid-removal state (its params already live in the
                # rewritten lower ids)
                logger.warning("old shard checkpoint %s missing "
                               "(crashed downsize rerun); continuing",
                               path)
                continue
            # ids below the new count get REWRITTEN, never removed: a
            # missing one means genuine loss — fail before overwriting
            # anything
            raise FileNotFoundError(
                f"missing PS shard checkpoint {path} (not explicable "
                "by a crashed rerun; refusing to rewrite a partial set)")
        found_any = True
        ingest(path)
    # crash recovery: a previous repartition run killed between its
    # batched renames can leave a parameter ONLY in a leftover tmp file
    # (its old home already renamed away, its new home not yet) — ingest
    # tmps so a rerun never silently drops it. Values are identical
    # where duplicated (repartition only moves), so setdefault is safe.
    # Tmps are NOT deleted here: until the new canonical files land they
    # may hold a parameter's only copy; stale ones are removed after the
    # rename phase below.
    for name in sorted(os.listdir(directory)):
        if name.startswith("ps-shard-") and ".tmp" in name and \
                name.endswith(".npz"):
            found_any = True
            ingest(os.path.join(directory, name), tolerate_torn=True)
    if not found_any or not params:
        raise FileNotFoundError(
            f"no restorable PS shard checkpoints under {directory}")

    specs = {n: int(a.nbytes) for n, a in params.items()}
    assignment = partition_params(specs, new_num_shards)

    # two phases: write EVERY tmp file, then rename them all. Renaming as
    # we go would destroy a parameter's only on-disk copy (old shard file
    # overwritten) before its new home is written — a mid-run crash must
    # leave either the complete old layout or the complete new one
    # recoverable, never a file set missing parameters. Tmp names carry
    # this run's pid so a rerun never overwrites a PREVIOUS run's
    # leftover tmp (which may hold a parameter's only surviving copy).
    tmps = []
    for shard in range(new_num_shards):
        payload = {"__version__": np.asarray(version, np.int64)}
        for name, target in assignment.items():
            if target != shard:
                continue
            payload[f"p/{name}"] = params[name]
            for sname, sval in slots.get(name, {}).items():
                payload[f"s/{name}/{sname}"] = sval
        path = _shard_path(directory, shard)
        tmp = path + f".tmp{os.getpid()}.npz"
        np.savez(tmp, **payload)
        tmps.append((tmp, path))
    for tmp, path in tmps:
        os.replace(tmp, path)
    for i in range(new_num_shards, old_num_shards):
        try:
            os.remove(_shard_path(directory, i))
        except OSError:
            pass
    # every parameter is now in a canonical file: leftover tmps (this
    # run's are renamed away already; earlier crashed runs') are safe to
    # drop
    for name in os.listdir(directory):
        if name.startswith("ps-shard-") and ".tmp" in name and \
                name.endswith(".npz"):
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass
    logger.info(
        "repartitioned %d params across %d -> %d PS shards (version %d)",
        len(params), old_num_shards, new_num_shards, version,
    )
    return assignment


def main(argv=None) -> int:
    """CLI for the migration driver:

        python -m dlrover_tpu.ps.repartition CKPT_DIR OLD_N NEW_N

    Run between stopping the old shards and starting the new ones
    (``start_ps_shard(..., restore=True, num_shards=NEW_N)``), then bump
    the global cluster version so workers re-resolve.
    """
    import argparse

    p = argparse.ArgumentParser(
        description="Repartition PS shard checkpoints for a new shard "
                    "count (offline, atomic).")
    p.add_argument("directory")
    p.add_argument("old_num_shards", type=int)
    p.add_argument("new_num_shards", type=int)
    args = p.parse_args(argv)
    assignment = repartition_checkpoint(
        args.directory, args.old_num_shards, args.new_num_shards)
    per_shard = {}
    for name, shard in assignment.items():
        per_shard[shard] = per_shard.get(shard, 0) + 1
    print(f"repartitioned {len(assignment)} params across "
          f"{args.new_num_shards} shards: {dict(sorted(per_shard.items()))}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
