"""Worker-side PS cluster view: discovery, partitioning, pull/push.

Role parity: the worker half of the reference's PS strategy — TF workers
resolve the PS cluster from TF_CONFIG kept fresh by the failover watcher
(``dlrover/trainer/tensorflow/failover/tensorflow_failover.py:33-144``) and
the variable placer spreads variables over PS tasks. Here:

- discovery: ``query_ps_nodes`` rpc against the distributed master
  (``servicer.py`` parity) with a KV-store fallback (``ps/addr/{i}`` keys)
  that the local/standalone path uses;
- placement: deterministic greedy size-balanced assignment of parameter
  names to shards — every worker computes the same mapping from the same
  specs, so there is no placement metadata service;
- elasticity: the master's cluster-version handshake
  (``elastic_ps.ElasticPsService``) signals membership changes; workers
  re-resolve addresses and re-pull.
"""

from __future__ import annotations

import heapq
import time
from typing import Dict, List, Optional, Sequence, Tuple

import grpc
import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.ps import wire
from dlrover_tpu.ps.server import PS_METHOD

logger = get_logger("ps.client")


def partition_params(specs: Dict[str, int], num_shards: int) -> Dict[str, int]:
    """name -> shard id; greedy bin-pack by byte size, deterministic.

    Sorting by (-size, name) then assigning each param to the least-loaded
    shard gives every worker the identical mapping with balanced bytes.
    """
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    heap: List[Tuple[int, int]] = [(0, i) for i in range(num_shards)]
    heapq.heapify(heap)
    assignment: Dict[str, int] = {}
    for name in sorted(specs, key=lambda n: (-specs[n], n)):
        load, shard = heapq.heappop(heap)
        assignment[name] = shard
        heapq.heappush(heap, (load + specs[name], shard))
    return assignment


class PsClusterClient:
    """Talks to every PS shard; presents one logical parameter dict."""

    def __init__(self, addrs: Sequence[str],
                 master_client=None, rpc_timeout: float = 60.0,
                 bulk_timeout: float = 600.0):
        self._master = master_client
        # every shard RPC carries a deadline: the fan-out blocks on
        # fut.result() for ALL shards, so one dead PS without a deadline
        # would hang the training step forever instead of raising into
        # the failover path (DLR001). Step-shaped ops (push/pull/stats)
        # ride rpc_timeout; bulk ops whose latency scales with MODEL
        # size, not step RTT (init streaming full params, checkpoint
        # writing to storage), get the larger bulk_timeout so a healthy
        # slow transfer is not misread as a dead shard.
        self._rpc_timeout = rpc_timeout
        self._bulk_timeout = max(bulk_timeout, rpc_timeout)
        self._addrs: List[str] = list(addrs)
        self._stubs: Dict[int, grpc.UnaryUnaryMultiCallable] = {}
        self._channels: Dict[int, grpc.Channel] = {}
        self._assignment: Dict[str, int] = {}
        self._by_shard: Dict[int, List[str]] = {}  # shard -> ordered names
        self._known_version = 0  # master global cluster version we built on

    def _set_assignment(self, assignment: Dict[str, int]) -> None:
        self._assignment = assignment
        self._by_shard = {}
        for name in sorted(assignment):
            self._by_shard.setdefault(assignment[name], []).append(name)

    # -- discovery ---------------------------------------------------------

    @classmethod
    def discover(cls, master_client, num_shards: Optional[int] = None,
                 timeout_s: float = 30.0) -> "PsClusterClient":
        """Resolve shard addresses via the master. Prefers the job-manager
        backed ``query_ps_nodes``; falls back to KV keys for local mode."""
        deadline = time.monotonic() + timeout_s
        while True:
            ps = master_client.query_ps_nodes()
            if ps.ready and ps.addrs:
                return cls(ps.addrs, master_client)
            addrs = cls._kv_addrs(master_client, num_shards)
            if addrs is not None:
                return cls(addrs, master_client)
            if time.monotonic() > deadline:
                raise TimeoutError("PS shards did not register in time")
            time.sleep(0.2)

    @staticmethod
    def _kv_addrs(master_client,
                  num_shards: Optional[int]) -> Optional[List[str]]:
        if num_shards is None:
            # the shard launcher announces the cluster size (ps/count) so a
            # worker racing shard registration can't adopt a partial list —
            # a partial view would compute a different placement than later
            # workers and silently split parameters
            count = master_client.kv_store_get("ps/count")
            if count:
                num_shards = int(count)
        addrs: List[str] = []
        i = 0
        while True:
            value = master_client.kv_store_get(f"ps/addr/{i}")
            if not value:
                break
            addr, _, gen = value.partition("|")
            if gen and num_shards is not None and gen != str(num_shards):
                # written by a different-sized cluster generation: a dead
                # endpoint, never a live one
                break
            addrs.append(addr)
            i += 1
        if not addrs:
            return None
        if num_shards is not None:
            if len(addrs) < num_shards:
                return None  # still registering
            addrs = addrs[:num_shards]
        return addrs

    # -- channels ----------------------------------------------------------

    def _stub(self, shard: int) -> grpc.UnaryUnaryMultiCallable:
        if shard not in self._stubs:
            channel = grpc.insecure_channel(
                self._addrs[shard],
                options=[
                    ("grpc.max_send_message_length", 1024 * 1024 * 1024),
                    ("grpc.max_receive_message_length", 1024 * 1024 * 1024),
                ],
            )
            self._channels[shard] = channel
            self._stubs[shard] = channel.unary_unary(
                PS_METHOD,
                request_serializer=wire.identity,
                response_deserializer=wire.identity,
            )
        return self._stubs[shard]

    def close(self):
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()
        self._stubs.clear()

    @property
    def num_shards(self) -> int:
        return len(self._addrs)

    # -- logical parameter ops --------------------------------------------

    def _fanout(self, frames: Dict[int, bytes], op: str,
                timeout: float = 0.0) -> Dict[int, tuple]:
        """Issue one call per shard concurrently (step latency = max shard
        RTT, not the sum — the point of sharding the PS) and collect.
        ``timeout`` overrides the step-shaped default (bulk ops)."""
        futs = {shard: self._stub(shard).future(
                    frame, timeout=timeout or self._rpc_timeout)
                for shard, frame in frames.items()}
        out = {}
        for shard, fut in futs.items():
            meta, tensors = wire.unpack_frame(fut.result())
            if not meta.get("ok"):
                raise RuntimeError(f"PS {op} failed on shard {shard}: {meta}")
            out[shard] = (meta, tensors)
        return out

    def init(self, params: Dict[str, np.ndarray]) -> None:
        specs = {n: int(a.nbytes) for n, a in params.items()}
        self._set_assignment(partition_params(specs, self.num_shards))
        frames = {
            shard: wire.pack_frame(
                {"op": "init"}, {n: params[n] for n in names})
            for shard, names in self._by_shard.items()
        }
        self._fanout(frames, "init", timeout=self._bulk_timeout)

    def pull(self) -> Tuple[Dict[str, np.ndarray], int]:
        """Fetch all params; returns (params, max shard version)."""
        frames = {
            shard: wire.pack_frame({"op": "pull", "names": names})
            for shard, names in self._by_shard.items()
        }
        out: Dict[str, np.ndarray] = {}
        version = 0
        for meta, tensors in self._fanout(frames, "pull").values():
            out.update(tensors)
            version = max(version, int(meta.get("version", 0)))
        return out, version

    def push(self, grads: Dict[str, np.ndarray]) -> int:
        """Send grads to owning shards; PS applies updates server-side."""
        frames = {}
        for shard, names in self._by_shard.items():
            group = {n: grads[n] for n in names if n in grads}
            if group:
                frames[shard] = wire.pack_frame({"op": "push"}, group)
        version = 0
        for meta, _ in self._fanout(frames, "push").values():
            version = max(version, int(meta.get("version", 0)))
        return version

    def checkpoint(self, directory: Optional[str] = None) -> None:
        frames = {shard: wire.pack_frame({"op": "checkpoint",
                                          "dir": directory})
                  for shard in range(self.num_shards)}
        self._fanout(frames, "checkpoint", timeout=self._bulk_timeout)

    def total_params(self) -> int:
        """Parameters held across every shard (0 = nothing restored)."""
        frames = {shard: wire.pack_frame({"op": "stats"})
                  for shard in range(self.num_shards)}
        return sum(int(meta.get("num_params", 0))
                   for meta, _ in self._fanout(frames, "stats").values())

    def reassign(self, specs: Dict[str, int]) -> None:
        """Recompute the placement locally from parameter byte sizes —
        the post-resize path. Pure client-side: the resized cluster must
        already HOLD the (repartitioned) parameters; nothing is sent."""
        self._set_assignment(partition_params(specs, self.num_shards))

    # -- elasticity --------------------------------------------------------

    def membership_changed(self) -> bool:
        """Poll the master's global PS cluster version; on a bump, re-resolve
        shard addresses (same handshake the TF failover watcher does on
        TF_CONFIG change)."""
        if self._master is None:
            return False
        version = self._master.get_cluster_version("global", "worker", 0)
        if version == self._known_version:
            return False
        addrs = self._kv_addrs(self._master, None)
        ps = self._master.query_ps_nodes()
        if ps.ready and ps.addrs:
            addrs = ps.addrs
        if not addrs:
            # resolution not ready yet — leave _known_version unconsumed so
            # the next check retries instead of pinning dead addresses
            return False
        logger.info("PS cluster version %d -> %d: re-resolved %d shards",
                    self._known_version, version, len(addrs))
        self._known_version = version
        old_count = len(self._addrs)
        self.close()
        self._addrs = list(addrs)
        # same shard count => same-placement migration (addresses moved,
        # mapping unchanged). ANY count change invalidates the placement
        # — keeping it would push/pull against a different partition than
        # other workers compute (silent parameter split on grow, dead
        # endpoints on shrink). The migration driver must move params via
        # checkpoint/restore before bumping the version; workers then
        # fail fast on the empty placement instead of diverging.
        if len(self._addrs) != old_count and self._assignment:
            logger.warning(
                "PS cluster resized %d -> %d shards: invalidating the "
                "parameter placement; restore from checkpoint to resume",
                old_count, len(self._addrs),
            )
            self._set_assignment({})
        return True
