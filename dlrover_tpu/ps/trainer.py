"""Asynchronous PS training loop.

Role parity: the reference's PS-strategy trainer — TF estimator workers
computing grads and letting the PS apply them asynchronously
(``dlrover/trainer/tensorflow/executor/estimator_executor.py``), with
elasticity handled by the cluster-version handshake
(``failover/failover_client.py``). Here the worker computes grads with a
jitted jax function (TPU or CPU — recommendation models are typically CPU
workers, matching DeepRec) and push/pulls through ``PsClusterClient``.

The loop is genuinely asynchronous: no barrier with other workers, global
batch is emergent, staleness bounded only by the pull-compute-push cadence.
This is intentionally the opposite discipline from ``dlrover_tpu.parallel``'s
synchronous GSPMD path — it exists for the sparse/CPU workloads where the
reference uses PS.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.ps.client import PsClusterClient

logger = get_logger("ps.trainer")


def _flatten_named(params) -> Tuple[Dict[str, np.ndarray], Any, list]:
    """Pytree -> {path-name: array}; returns (dict, treedef, ordered names)."""
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(params)
    names, flat = [], {}
    for path, leaf in leaves_with_path:
        name = jax.tree_util.keystr(path)
        names.append(name)
        flat[name] = np.asarray(leaf)
    return flat, treedef, names


class AsyncPsTrainer:
    """Pull -> grad -> push loop against a PS cluster.

    ``loss_fn(params, batch) -> scalar`` is differentiated and jitted once;
    parameter structure is captured at ``init_params``.
    """

    def __init__(self, loss_fn: Callable, cluster: PsClusterClient,
                 master_client=None, membership_check_every: int = 8,
                 report_every: int = 16):
        self._cluster = cluster
        self._master = master_client
        self._check_every = membership_check_every
        self._report_every = report_every
        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn))
        self._treedef = None
        self._names: list = []
        self._step = 0
        self._start_ts = time.time()
        # parameter byte sizes, captured at init: all that's needed to
        # recompute the placement after a cluster resize (no full-model
        # copy pinned on the worker)
        self._specs: Optional[Dict[str, int]] = None

    # -- setup -------------------------------------------------------------

    def init_params(self, params) -> None:
        flat, self._treedef, self._names = _flatten_named(params)
        self._cluster.init(flat)
        self._specs = {n: int(a.nbytes) for n, a in flat.items()}

    def _unflatten(self, flat: Dict[str, np.ndarray]):
        return jax.tree_util.tree_unflatten(
            self._treedef, [flat[n] for n in self._names])

    # -- the loop ----------------------------------------------------------

    def step(self, batch) -> float:
        """One async step: pull fresh params, compute grads, push."""
        if self._step and self._check_every and \
                self._step % self._check_every == 0:
            self._cluster.membership_changed()
        flat, _version = self._cluster.pull()
        if not flat:
            # a resize invalidated the placement. The worker knows every
            # parameter's byte size, so it recomputes the placement
            # locally — but ONLY against a cluster that demonstrably
            # holds the repartitioned parameters. Re-seeding an empty
            # cluster from a worker's stale snapshot would silently
            # discard other workers' progress and reset optimizer state.
            if self._specs is None:
                raise RuntimeError(
                    "PS pull returned no parameters and no parameter "
                    "specs are known; initialize or restore first")
            held = self._cluster.total_params()
            if held != len(self._specs):
                raise RuntimeError(
                    f"PS cluster holds {held} of {len(self._specs)} "
                    "parameters after the resize; repartition + restore "
                    "the checkpoint before resuming workers")
            logger.info("PS placement invalidated (resize): recomputed "
                        "against %d shards", self._cluster.num_shards)
            self._cluster.reassign(self._specs)
            flat, _version = self._cluster.pull()
            if not flat:
                raise RuntimeError("PS pull still empty after placement "
                                   "recompute; cluster is not restored")
            # validate by NAME, not just count: a same-size foreign
            # checkpoint (or a double-held leftover from a crashed
            # repartition) must not pass as restored state
            if set(flat) != set(self._specs):
                missing = sorted(set(self._specs) - set(flat))[:5]
                raise RuntimeError(
                    "PS cluster parameter names do not match this "
                    f"worker's model after the resize (missing e.g. "
                    f"{missing}); wrong or partial checkpoint restored")
        params = self._unflatten(flat)
        loss, grads = self._grad_fn(params, batch)
        gflat, _, _ = _flatten_named(grads)
        self._cluster.push(gflat)
        self._step += 1
        if self._master is not None and self._report_every and \
                self._step % self._report_every == 0:
            self._master.report_global_step(self._step)
        return float(loss)

    @property
    def global_step(self) -> int:
        return self._step

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self, directory: Optional[str] = None) -> None:
        self._cluster.checkpoint(directory)
