"""Elastic checkpoint/resume: async Orbax saves with GSPMD resharding.

Role parity: the reference's elastic FSDP checkpoint
(``atorch/atorch/utils/fsdp_save_util.py``) + data-shard checkpoints
(``batch_dataset_manager.py:157-203``).
"""

from dlrover_tpu.checkpoint.manager import (
    CheckpointInterval,
    ElasticCheckpointManager,
    HostSnapshot,
    abstract_like,
)
from dlrover_tpu.checkpoint.replication import (
    ReplicaStore,
    SnapshotReplicator,
    fetch_tree,
    start_replica_server,
)

__all__ = [
    "CheckpointInterval",
    "ElasticCheckpointManager",
    "HostSnapshot",
    "ReplicaStore",
    "SnapshotReplicator",
    "abstract_like",
    "fetch_tree",
    "start_replica_server",
]
