"""Elastic model checkpointing over Orbax.

Role parity: ``atorch/atorch/utils/fsdp_save_util.py:97-549`` — the
reference saves per-rank FSDP flat params + meta and hand-reshards them on
load to a different world size. On TPU none of that machinery is needed:
GSPMD + Orbax make resharding native. Saving writes the *global* logical
arrays (each host contributing its shards); restoring materializes them
directly into whatever ``NamedSharding``s the *new* mesh wants. A job that
went from 32 to 16 hosts restores the same checkpoint unchanged.

Also the parity point for the reference's async-save design goal
(``docs/blogs/stabilize_llm_training_cn.md:215``: 10 min → 1 min saves):
``enable_async_checkpointing`` stages device arrays to host DRAM and
writes in a background thread, so the training step resumes immediately.

Data-shard state rides along: the master's shard checkpoint string
(``task_manager.get_shard_checkpoint``) is saved next to the model state so
a restored job resumes mid-epoch without re-reading consumed data.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger("checkpoint.manager")


@dataclass
class CheckpointInterval:
    """Cadence helper (reference: ``trainer/torch/elastic.py:170``).

    ``steps`` and ``secs`` compose with OR: save when either elapses.
    """

    steps: int = 0
    secs: float = 0.0
    _last_step: int = 0
    _last_time: float = 0.0

    def __post_init__(self):
        self._last_time = time.time()

    def should_save(self, step: int) -> bool:
        due = False
        if self.steps and step - self._last_step >= self.steps:
            due = True
        if self.secs and time.time() - self._last_time >= self.secs:
            due = True
        return due

    def mark_saved(self, step: int):
        self._last_step = step
        self._last_time = time.time()


def abstract_like(state: Any, sharding_tree: Any = None) -> Any:
    """Build the abstract (shape/dtype/sharding) target for a restore.

    Pass the sharding tree of the *current* mesh — this is where cross-
    world-size resharding happens: the checkpoint holds global arrays, and
    Orbax lays them out into these shardings on load.
    """
    if sharding_tree is None:
        return jax.eval_shape(lambda x: x, state)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        jax.eval_shape(lambda x: x, state),
        sharding_tree,
    )


class ElasticCheckpointManager:
    """Save/restore TrainState + metadata, async by default.

    The directory layout is Orbax-standard (one numbered subdir per step),
    so checkpoints written at one world size restore at any other.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        async_save: Optional[bool] = None,
        save_interval: Optional[CheckpointInterval] = None,
    ):
        import orbax.checkpoint as ocp

        from dlrover_tpu.common.config import get_context

        self._ocp = ocp
        if async_save is None:
            async_save = get_context().ckpt_async
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._manager = ocp.CheckpointManager(self.directory, options=options)
        self.interval = save_interval or CheckpointInterval()

    # -- save ----------------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        metadata: Optional[Dict] = None,
        shard_checkpoint: str = "",
        force: bool = False,
    ) -> bool:
        """Queue a checkpoint; returns True if a save was started.

        With async on, this returns as soon as device arrays are staged to
        host memory; the disk write happens in the background.
        """
        if not force and not self.interval.should_save(step):
            return False
        ocp = self._ocp
        meta = dict(metadata or {})
        meta["save_wall_time"] = time.time()
        args = {"state": ocp.args.StandardSave(state),
                "meta": ocp.args.JsonSave(meta)}
        if shard_checkpoint:
            args["data_shards"] = ocp.args.JsonSave(
                {"checkpoint": shard_checkpoint}
            )
        saved = self._manager.save(step, args=ocp.args.Composite(**args))
        if saved:
            self.interval.mark_saved(step)
            logger.info("checkpoint %d queued to %s", step, self.directory)
        return bool(saved)

    def wait(self):
        """Block until queued async saves hit disk."""
        self._manager.wait_until_finished()

    # -- restore -------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def restore(
        self,
        abstract_state: Any,
        step: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """Restore into the shardings carried by ``abstract_state``.

        Returns {"state": ..., "meta": {...}, "shard_checkpoint": str}, or
        None if the directory holds no checkpoint.
        """
        ocp = self._ocp
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        items = self._manager.item_metadata(step)
        args = {"state": ocp.args.StandardRestore(abstract_state),
                "meta": ocp.args.JsonRestore()}
        try:
            has_shards = items is not None and "data_shards" in items.keys()
        except (AttributeError, TypeError):
            has_shards = False
        if has_shards:
            args["data_shards"] = ocp.args.JsonRestore()
        restored = self._manager.restore(step, args=ocp.args.Composite(**args))
        out = {
            "state": restored["state"],
            "meta": restored["meta"] or {},
            "shard_checkpoint": "",
            "step": step,
        }
        if has_shards and restored.get("data_shards"):
            out["shard_checkpoint"] = restored["data_shards"].get(
                "checkpoint", ""
            )
        logger.info("restored checkpoint step=%d from %s", step, self.directory)
        return out

    def close(self):
        self._manager.close()
