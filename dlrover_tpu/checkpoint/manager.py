"""Elastic model checkpointing over Orbax.

Role parity: ``atorch/atorch/utils/fsdp_save_util.py:97-549`` — the
reference saves per-rank FSDP flat params + meta and hand-reshards them on
load to a different world size. On TPU none of that machinery is needed:
GSPMD + Orbax make resharding native. Saving writes the *global* logical
arrays (each host contributing its shards); restoring materializes them
directly into whatever ``NamedSharding``s the *new* mesh wants. A job that
went from 32 to 16 hosts restores the same checkpoint unchanged.

Also the parity point for the reference's async-save design goal
(``docs/blogs/stabilize_llm_training_cn.md:215``: 10 min → 1 min saves):
``enable_async_checkpointing`` stages device arrays to host DRAM and
writes in a background thread, so the training step resumes immediately.

Data-shard state rides along: the master's shard checkpoint string
(``task_manager.get_shard_checkpoint``) is saved next to the model state so
a restored job resumes mid-epoch without re-reading consumed data.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    SpanName,
    emit_event,
    get_registry,
    names as tm,
    span,
)

logger = get_logger("checkpoint.manager")


@dataclass
class CheckpointInterval:
    """Cadence helper (reference: ``trainer/torch/elastic.py:170``).

    ``steps`` and ``secs`` compose with OR: save when either elapses.
    """

    steps: int = 0
    secs: float = 0.0
    _last_step: int = 0
    _last_time: float = 0.0

    def __post_init__(self):
        self._last_time = time.time()

    def should_save(self, step: int) -> bool:
        due = False
        if self.steps and step - self._last_step >= self.steps:
            due = True
        if self.secs and time.time() - self._last_time >= self.secs:
            due = True
        return due

    def mark_saved(self, step: int):
        self._last_step = step
        self._last_time = time.time()


def abstract_like(state: Any, sharding_tree: Any = None) -> Any:
    """Build the abstract (shape/dtype/sharding) target for a restore.

    Pass the sharding tree of the *current* mesh — this is where cross-
    world-size resharding happens: the checkpoint holds global arrays, and
    Orbax lays them out into these shardings on load.
    """
    if sharding_tree is None:
        return jax.eval_shape(lambda x: x, state)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        jax.eval_shape(lambda x: x, state),
        sharding_tree,
    )


@dataclass
class HostSnapshot:
    """An in-process, host-DRAM copy of a TrainState — the live-recovery
    analogue of the staging mirror, with the storage round-trip removed.

    Where the mirror layer copies a *committed Orbax step* into tmpfs so
    a restarted process restores from DRAM, ``HostSnapshot`` keeps the
    *live* state in this process's own heap so a surviving process never
    restores at all: the executor drains its in-flight window, takes one
    snapshot (a single ``device_get``), rebuilds the mesh for the new
    world, and ``device_put``s the snapshot against the new shardings —
    GSPMD lays the global arrays out for the survivor topology exactly
    as an Orbax reshard-on-load would, minus serialization, storage, and
    process boot. Leaves are host numpy arrays: donation-safe (XLA never
    owned them) and immune to peer/device loss.
    """

    step: int
    tree: Any
    meta: Dict[str, Any]

    @classmethod
    def take(cls, state: Any, **meta) -> "HostSnapshot":
        """One device sync: pull every leaf to host DRAM. Callers drain
        in-flight work first so this waits only on the last step.

        On the CPU backend ``device_get`` can return numpy views that
        ALIAS the live XLA buffers (host memory IS device memory there
        — the same zero-copy family as the Orbax adjacency hang): a
        donated train step dispatched after ``take()`` would then
        scribble over the "snapshot". One host-side copy per leaf makes
        the snapshot genuinely immune to later donation; accelerator
        backends skip it (their device_get is a real D2H copy)."""
        reg = get_registry()
        t0 = time.monotonic()
        with span(SpanName.STATE_SNAPSHOT):
            tree = jax.device_get(state)
            if _on_cpu_backend(state):
                import numpy as _np

                tree = jax.tree.map(
                    lambda x: _np.array(x, copy=True)
                    if isinstance(x, _np.ndarray) else x,
                    tree,
                )
        snap_s = time.monotonic() - t0
        reg.histogram(
            tm.SNAPSHOT_TIME,
            help="host-DRAM TrainState snapshot (device_get) seconds",
        ).observe(snap_s)
        step = int(tree.step) if hasattr(tree, "step") else -1
        emit_event(EventKind.STATE_SNAPSHOT, step=step,
                   snapshot_seconds=round(snap_s, 3))
        return cls(step=step, tree=tree, meta=dict(meta))

    def restore(self, sharding_tree: Any) -> Any:
        """Materialize the snapshot into ``sharding_tree`` — the new
        mesh's NamedShardings. ``device_put`` against them IS the
        reshard: XLA scatters each host array into the survivor
        topology's layout (the in-memory twin of Orbax's
        reshard-on-load)."""
        return jax.device_put(self.tree, sharding_tree)

    def nbytes(self) -> int:
        """Host bytes this snapshot holds. Non-numpy leaves (python
        scalars, 0-d device remnants) are sized through ``np.asarray``
        instead of silently counting 0 — the replica-budget admission
        prices plans off this number."""
        import numpy as np

        total = 0
        for leaf in jax.tree.leaves(self.tree):
            n = getattr(leaf, "nbytes", None)
            if n is None:
                try:
                    n = np.asarray(leaf).nbytes
                except (TypeError, ValueError):
                    n = 0
            total += int(n)
        return total


def _on_cpu_backend(state: Any) -> bool:
    """True when the state's device arrays live on the CPU backend (the
    zero-copy-aliasing platform the donation-safety copies exist for)."""
    leaves = [x for x in jax.tree.leaves(state) if isinstance(x, jax.Array)]
    if not leaves:
        return False
    try:
        return {d.platform for d in leaves[0].devices()} == {"cpu"}
    except Exception as e:  # noqa: BLE001 — conservative: copy when unsure
        logger.debug("could not read device platform (%s: %s); assuming "
                     "cpu for the donation-safety copy",
                     type(e).__name__, e)
        return True


def _rematerialize(state: Any) -> Any:
    """Copy restored arrays into fresh XLA-owned buffers.

    Orbax materializes restored ``jax.Array``s over buffers that (on the
    CPU backend) can alias tensorstore-owned host memory. The train step
    is compiled with ``donate_argnums``, so the first step after a
    restore would DONATE those aliased buffers — XLA then writes into /
    frees memory it does not own. Observed as a segfault or a wedged
    dispatch once another Orbax manager has touched the process (the
    tests/test_checkpoint_trainer.py + tests/test_executor.py adjacency
    hang). One cheap copy per restore makes every restored leaf
    donation-safe; sharding is preserved. The whole tree goes through
    ONE jitted program (not a per-leaf ``jnp.copy`` — that would
    compile hundreds of trivial executables on a large model's first
    restore, a real MTTR tax)."""
    return _copy_tree(state)


@jax.jit
def _copy_tree(tree: Any) -> Any:
    import jax.numpy as jnp

    return jax.tree.map(jnp.copy, tree)


def _decouple_from_donation(state: Any) -> Any:
    """The WRITE-side twin of ``_rematerialize``: on the CPU backend,
    Orbax's async save zero-copy-references the live device buffers
    (host memory IS device memory there), while the training loop's
    next step DONATES those same buffers — the background write then
    persists whatever the donated computation scribbled over them. A
    NaN landing one step after a save used to poison the freshly
    "committed" checkpoint this way (the rollback target!), surfacing
    as the rollback tests failing only after another Orbax manager had
    warmed the background pools enough for the write to lose the race.
    One device-side copy per save hands Orbax buffers nothing ever
    donates. TPU/GPU backends skip it: there Orbax's async save stages
    a host copy before returning, which decouples donation already."""
    leaves = [x for x in jax.tree.leaves(state) if isinstance(x, jax.Array)]
    if not leaves:
        return state
    if not _on_cpu_backend(state):
        return state
    return _copy_tree(state)


class ElasticCheckpointManager:
    """Save/restore TrainState + metadata, async by default.

    The directory layout is Orbax-standard (one numbered subdir per step),
    so checkpoints written at one world size restore at any other.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        async_save: Optional[bool] = None,
        save_interval: Optional[CheckpointInterval] = None,
        staging_dir: Optional[str] = None,
        run_identity: str = "",
    ):
        import orbax.checkpoint as ocp

        from dlrover_tpu.common.config import get_context

        # staging provenance token. A path-local uuid file alone cannot
        # survive the very outage staging exists for (primary root wiped
        # => the uuid is gone => a fresh uuid rejects the good mirror and
        # the job silently restarts from scratch). A caller-stable run
        # identity survives primary loss while still fencing out another
        # run reusing the path. RUN_ID (job name + launch epoch, set by
        # the scalers) is preferred over the bare JOB_NAME: a brand-new
        # job reusing the same name and checkpoint path — the common
        # rerun pattern — must NOT adopt the previous run's staged
        # weights, which a name-only token would allow.
        self._run_identity = (
            run_identity
            or os.environ.get(NodeEnv.RUN_ID, "")
            or os.environ.get(NodeEnv.JOB_NAME, "")
        )

        self._ocp = ocp
        ctx = get_context()
        if async_save is None:
            async_save = ctx.ckpt_async
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._manager = ocp.CheckpointManager(self.directory, options=options)
        self.interval = save_interval or CheckpointInterval()
        # Host-DRAM staging (reference: Flash Checkpoint / the <90 s
        # restore budget, stabilize_llm_training_cn.md:209-216): after a
        # save commits, the step dir is mirrored into tmpfs so a restart
        # on the same host restores from DRAM instead of (remote) storage.
        self._staging_root: Optional[str] = None
        if staging_dir is None and ctx.ckpt_host_staging:
            shm = "/dev/shm"
            if (
                os.path.isdir(shm)
                and os.access(shm, os.W_OK)
                and not self.directory.startswith(shm)
            ):
                staging_dir = os.path.join(
                    shm, "dlrover_tpu_ckpt",
                    hashlib.md5(self.directory.encode()).hexdigest()[:12],
                )
        if staging_dir:
            self._staging_root = os.path.abspath(staging_dir)
            os.makedirs(self._staging_root, exist_ok=True)
        reg = get_registry()
        self._c_saves = reg.counter(
            tm.CKPT_SAVES, help="checkpoint saves queued")
        self._h_save = reg.histogram(
            tm.CKPT_SAVE_TIME,
            help="host time staging a save (async: device->host copy "
                 "before the background write)")
        self._h_mirror = reg.histogram(
            tm.CKPT_MIRROR_TIME, help="host-DRAM staging mirror copy time")
        self._c_mirror_timeouts = reg.counter(
            tm.CKPT_MIRROR_TIMEOUTS,
            help="staging mirrors still uncommitted at a wait() deadline")
        self._h_restore = reg.histogram(
            tm.CKPT_RESTORE_TIME, help="restore wall time")
        self._c_restores = reg.counter(
            tm.CKPT_RESTORES, help="successful restores")
        self._mirror_lock = threading.Lock()
        self._mirror_threads: list = []
        # mirror THREAD OBJECTS that already consumed a full join
        # timeout (wait() only polls these afterwards). Keyed by object,
        # never by ident: idents are recycled after a thread exits, and
        # a fresh healthy mirror inheriting a stale flag would get a
        # 0-second join on the preemption exit path
        self._mirror_timed_out: set = set()

    # -- save ----------------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        metadata: Optional[Dict] = None,
        shard_checkpoint: str = "",
        force: bool = False,
    ) -> bool:
        """Queue a checkpoint; returns True if a save was started.

        With async on, this returns as soon as device arrays are staged to
        host memory; the disk write happens in the background.
        """
        if not force and not self.interval.should_save(step):
            return False
        state = _decouple_from_donation(state)
        ocp = self._ocp
        meta = dict(metadata or {})
        meta["save_wall_time"] = time.time()
        args = {"state": ocp.args.StandardSave(state),
                "meta": ocp.args.JsonSave(meta)}
        if shard_checkpoint:
            args["data_shards"] = ocp.args.JsonSave(
                {"checkpoint": shard_checkpoint}
            )
        t0 = time.monotonic()
        with span(SpanName.CKPT_SAVE_STAGE, step=step):
            saved = self._manager.save(
                step, args=ocp.args.Composite(**args))
        if saved:
            stage_s = time.monotonic() - t0
            self._c_saves.inc()
            self._h_save.observe(stage_s)
            emit_event(EventKind.CKPT_SAVE, step=step,
                       stage_seconds=round(stage_s, 3), forced=force)
            self.interval.mark_saved(step)
            logger.info("checkpoint %d queued to %s", step, self.directory)
            if self._staging_root is not None:
                # mirror once the async write commits, off the hot path
                thread = threading.Thread(
                    target=self._wait_and_mirror, args=(step,), daemon=True
                )
                self._mirror_threads = [
                    t for t in self._mirror_threads if t.is_alive()
                ] + [thread]
                thread.start()
        return bool(saved)

    def wait(self, mirror_timeout: float = 120.0) -> bool:
        """Block until queued async saves hit disk (and their staging
        mirrors complete).

        Returns ``timed_out``: True when a staging-mirror thread was
        still alive after ``mirror_timeout`` — the host-DRAM mirror for
        some step never committed, so a storage-outage restore would
        fall back to an OLDER staged step. Callers on exit paths (the
        preemption drain, ``finalize``) surface this instead of
        silently proceeding; the primary (Orbax) copy is unaffected
        either way."""
        self._manager.wait_until_finished()
        timed_out = False
        pending: list = []
        for thread in self._mirror_threads:
            if thread.is_alive():
                # a thread that already burned one full timeout is only
                # POLLED afterwards: repeated wait() calls (e.g. the
                # preemption drain's latest_checkpoint_step + finalize
                # back-to-back) must not stack 120s stalls inside the
                # bounded grace window
                already_flagged = thread in self._mirror_timed_out
                thread.join(timeout=0.0 if already_flagged
                            else mirror_timeout)
            if thread.is_alive():
                timed_out = True
                pending.append(thread)
                if thread not in self._mirror_timed_out:
                    self._mirror_timed_out.add(thread)
                    self._c_mirror_timeouts.inc()
                    emit_event(EventKind.CKPT_MIRROR_TIMEOUT,
                               error_code="CKPT_MIRROR_TIMEOUT",
                               timeout_seconds=mirror_timeout)
                    logger.error(
                        "[CKPT_MIRROR_TIMEOUT] staging mirror thread %s "
                        "still running after %.0fs: the host-DRAM mirror "
                        "for its step never committed (primary "
                        "checkpoint unaffected)",
                        thread.name, mirror_timeout,
                    )
            else:
                self._mirror_timed_out.discard(thread)
        # keep only the still-alive threads: a later wait() can still
        # observe them instead of forgetting the in-flight mirror
        self._mirror_threads = pending
        self._mirror_timed_out &= set(pending)
        return timed_out

    # -- host-DRAM staging ----------------------------------------------------

    def _step_dir(self, root: str, step: int) -> str:
        return os.path.join(root, str(step))

    def _newer_step_committed(self, step: int) -> bool:
        """Filesystem-only (the mirror thread must never touch the
        non-thread-safe Orbax manager): a committed step dir numbered
        above ``step``."""
        try:
            return any(
                name.isdigit() and int(name) > step
                for name in os.listdir(self.directory)
            )
        except OSError:
            return False

    def _wait_and_mirror(self, step: int, deadline_s: float = 600.0):
        """Mirror once the step commits. Orbax's CheckpointManager is not
        thread-safe, so this thread never touches it: on posix the atomic
        rename of the tmp dir to ``<root>/<step>`` IS the commit marker —
        poll for that instead of wait_until_finished()."""
        import time as _time

        step_dir = self._step_dir(self.directory, step)
        deadline = _time.monotonic() + deadline_s
        try:
            while not os.path.isdir(step_dir):
                if _time.monotonic() > deadline:
                    logger.warning(
                        "step %d never committed; skipping staging", step
                    )
                    return
                if self._newer_step_committed(step):
                    # commits are ordered, so a NEWER numbered dir with
                    # this one absent means max_to_keep already deleted
                    # it (or will): stop polling instead of spinning to
                    # the deadline and stalling wait() — the newer
                    # step's own mirror supersedes this one anyway
                    logger.info(
                        "step %d superseded before mirroring; skipping",
                        step,
                    )
                    return
                _time.sleep(0.5)
            self._mirror_to_staging(step)
        except Exception:  # noqa: BLE001 — staging is best-effort
            logger.exception("staging mirror for step %d failed", step)

    def _mirror_to_staging(self, step: int):
        src = self._step_dir(self.directory, step)
        if not os.path.isdir(src):
            return
        with self._mirror_lock:  # serialize: mirrors must not interleave
            # reclaim tmp dirs orphaned by a crash mid-copy (the exact
            # preemption staging exists for): the keep-newest cleanup
            # below only understands numbered step dirs, so without this
            # every crashed mirror permanently leaks tmpfs until the
            # free-space gate silently disables staging altogether
            try:
                for name in os.listdir(self._staging_root):
                    if name.startswith(".tmp_"):
                        shutil.rmtree(
                            os.path.join(self._staging_root, name),
                            ignore_errors=True,
                        )
            except OSError:
                pass
            newest = self.staged_step()
            if newest is not None and not self._staging_provenance_valid():
                # leftovers from a previous job at this checkpoint path:
                # clear them so staging works from this job's first save
                logger.info("clearing stale staging mirror (provenance "
                            "mismatch)")
                self._clear_staging()
                newest = None
            if newest is not None and (
                newest > step
                or (newest == step and self._staged_digest_valid(step))
            ):
                return  # an equal-or-newer valid step is already staged
            # size gate: a checkpoint bigger than (half the) free tmpfs
            # would just burn read bandwidth and fail with ENOSPC
            try:
                ckpt_bytes = sum(
                    os.path.getsize(os.path.join(r, f))
                    for r, _d, files in os.walk(src) for f in files
                )
                free = shutil.disk_usage(self._staging_root).free
            except OSError:
                ckpt_bytes, free = 0, 0
            if ckpt_bytes and ckpt_bytes * 2 > free:
                logger.warning(
                    "skipping host-DRAM staging: checkpoint %.1f GB vs "
                    "%.1f GB free tmpfs", ckpt_bytes / 1e9, free / 1e9,
                )
                return
            tmp = os.path.join(self._staging_root, f".tmp_{step}")
            dst = self._step_dir(self._staging_root, step)
            shutil.rmtree(tmp, ignore_errors=True)
            t0 = time.monotonic()
            try:
                with span(SpanName.CKPT_MIRROR, step=step):
                    digest = self._dir_digest(src)
                    shutil.copytree(src, tmp)
                    shutil.rmtree(dst, ignore_errors=True)
                    os.rename(tmp, dst)
                with open(dst + ".digest", "w") as f:
                    f.write(digest)
                self._write_provenance()
                # keep only the newest staged step: DRAM is precious
                for name in os.listdir(self._staging_root):
                    base = name.split(".")[0]
                    if base.isdigit() and int(base) < step:
                        path = os.path.join(self._staging_root, name)
                        if os.path.isdir(path):
                            shutil.rmtree(path, ignore_errors=True)
                        else:
                            try:
                                os.remove(path)
                            except OSError:
                                pass
                mirror_s = time.monotonic() - t0
                self._h_mirror.observe(mirror_s)
                emit_event(EventKind.CKPT_MIRROR, step=step,
                           mirror_seconds=round(mirror_s, 3))
                logger.info("checkpoint %d staged to %s", step,
                            self._staging_root)
            except OSError as e:  # tmpfs full, races — never fail the job
                logger.warning("host-DRAM staging failed: %s", e)
                shutil.rmtree(tmp, ignore_errors=True)
                shutil.rmtree(dst, ignore_errors=True)

    def _primary_identity(self) -> str:
        """Identity token used for staging provenance. With a run
        identity (job name), the token is stable across loss of the
        primary root — the storage-outage case staging exists for.
        Otherwise: a uuid file created once per root; it survives a
        same-host restart, but a wiped-and-recreated root gets a new
        uuid (so an anonymous fresh job can never inherit a previous
        job's weights — at the cost of the outage fallback)."""
        if self._run_identity:
            return f"job:{self._run_identity}"
        marker = os.path.join(self.directory, ".dlrover_ckpt_id")
        try:
            with open(marker) as f:
                return f.read().strip()
        except OSError:
            pass
        import uuid

        ident = uuid.uuid4().hex
        try:
            tmp = f"{marker}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(ident)
            os.rename(tmp, marker)
            with open(marker) as f:  # racing writers: reread the winner
                return f.read().strip()
        except OSError:
            return ""

    def _write_provenance(self):
        ident = self._primary_identity()
        if not ident:
            return
        try:
            with open(os.path.join(self._staging_root, "PROVENANCE"),
                      "w") as f:
                f.write(ident)
        except OSError:
            pass

    def _staging_provenance_valid(self) -> bool:
        try:
            with open(os.path.join(self._staging_root, "PROVENANCE")) as f:
                recorded = f.read().strip()
        except OSError:
            return False
        ident = self._primary_identity()
        return bool(ident) and ident == recorded

    def _clear_staging(self):
        try:
            for name in os.listdir(self._staging_root):
                path = os.path.join(self._staging_root, name)
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
        except OSError:
            pass

    @staticmethod
    def _dir_digest(path: str) -> str:
        """Cheap content-identity fingerprint of a step dir: every file's
        relpath, size, and mtime. Guards staged restores against a stale
        mirror left by a PREVIOUS job at the same checkpoint path."""
        entries = []
        for root, _dirs, files in os.walk(path):
            for name in sorted(files):
                full = os.path.join(root, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append(
                    f"{os.path.relpath(full, path)}:{st.st_size}:"
                    f"{st.st_mtime_ns}"
                )
        return hashlib.sha256("\n".join(sorted(entries)).encode()).hexdigest()

    def _staged_digest_valid(self, step: int) -> bool:
        """The staged copy is trustworthy iff its recorded digest matches
        the primary step dir as it is NOW — or the primary step dir is
        gone entirely (the storage-outage fast-restart case)."""
        dst = self._step_dir(self._staging_root, step)
        try:
            with open(dst + ".digest") as f:
                recorded = f.read().strip()
        except OSError:
            return False
        src = self._step_dir(self.directory, step)
        if not os.path.isdir(src):
            if not os.path.isdir(self.directory):
                # the primary ROOT vanished after construction (the
                # constructor makedirs it, so a fresh job always has
                # one): storage outage — the mirror is the survivor
                logger.warning(
                    "adopting staged checkpoint step=%d: primary root "
                    "%s is GONE (storage outage path). If this is a "
                    "fresh run, these are a previous run's weights — "
                    "clear %s to start from scratch.",
                    step, self.directory, self._staging_root,
                )
                return True
            # root present but step missing: trust the mirror only for
            # the SAME run identity (a fresh job recreating the path
            # must not inherit the previous job's weights)
            ok = self._staging_provenance_valid()
            if ok:
                logger.warning(
                    "adopting staged checkpoint step=%d under identity "
                    "'%s' with an EMPTY primary %s. A same-named fresh "
                    "run inherits the previous run's weights here — set "
                    "%s (or pass run_identity) to fence runs apart.",
                    step, self._primary_identity(), self.directory,
                    NodeEnv.RUN_ID,
                )
            return ok
        return self._dir_digest(src) == recorded

    def staged_step(self) -> Optional[int]:
        """Newest step available in the host-DRAM staging mirror."""
        if self._staging_root is None or not os.path.isdir(
            self._staging_root
        ):
            return None
        steps = [
            int(n) for n in os.listdir(self._staging_root) if n.isdigit()
        ]
        return max(steps) if steps else None

    # -- restore -------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def restore_from_staging(
        self, abstract_state: Any
    ) -> Optional[Dict[str, Any]]:
        """Warm-restart fast path: restore the newest staged step from
        the host-DRAM mirror WITHOUT touching the primary directory.

        ``restore()`` consults the primary's step listing first; on a
        remote/flaky store that round-trip alone can dominate a restart
        budget. A same-host process restart (the agent's default
        recovery for a survivable failure when no process survived) can
        skip it: the mirror holds the newest step this host committed,
        digest/provenance-validated like any staged restore. Returns
        None when there is nothing staged or validation fails — callers
        fall back to ``restore()``.
        """
        if self._staging_root is None:
            return None
        step = self.staged_step()
        if step is None or not self._staged_digest_valid(step):
            return None
        t0 = time.monotonic()
        try:
            with span(SpanName.CKPT_RESTORE, source="staging"):
                out = self._restore_from(self._staging_root, step,
                                         abstract_state)
        except Exception:  # noqa: BLE001 — callers fall back to restore()
            logger.exception(
                "staging fast-path restore of step %d failed", step)
            return None
        restore_s = time.monotonic() - t0
        self._h_restore.observe(restore_s)
        self._c_restores.inc()
        emit_event(EventKind.CKPT_RESTORE, step=step,
                   restore_seconds=round(restore_s, 3), source="staging")
        logger.info("restored step %d from host-DRAM staging (no "
                    "primary round-trip)", step)
        return out

    def restore(
        self,
        abstract_state: Any,
        step: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """Restore into the shardings carried by ``abstract_state``.

        Prefers the host-DRAM staged copy when it holds the requested
        step (no storage round-trip). Returns {"state": ..., "meta":
        {...}, "shard_checkpoint": str}, or None if no checkpoint exists.
        """
        t0 = time.monotonic()
        with span(SpanName.CKPT_RESTORE):
            out = self._restore_any(abstract_state, step)
        if out is not None:
            restore_s = time.monotonic() - t0
            self._h_restore.observe(restore_s)
            self._c_restores.inc()
            emit_event(EventKind.CKPT_RESTORE, step=out.get("step"),
                       restore_seconds=round(restore_s, 3))
        return out

    def _restore_any(
        self,
        abstract_state: Any,
        step: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        staging_only = False
        explicit_step = step is not None
        if step is None:
            try:
                step = self.latest_step()
            except Exception:  # noqa: BLE001 — primary storage gone
                step = None
            if step is None and self._staging_root is not None:
                # primary storage lost entirely: the host-DRAM mirror is
                # the restore source of last resort (digest/provenance
                # checked below like any other staged restore). The
                # primary has no such step, so there is no fallback:
                # failed validation means "no checkpoint", not a crash.
                step = self.staged_step()
                staging_only = step is not None
        if step is None:
            return None
        staged_already_failed = False
        if (
            self._staging_root is not None
            and self.staged_step() == step
            and self._staged_digest_valid(step)
        ):
            try:
                out = self._restore_from(self._staging_root, step,
                                         abstract_state)
                logger.info(
                    "restored checkpoint step=%d from host-DRAM staging",
                    step,
                )
                return out
            except Exception:  # noqa: BLE001 — fall back to the real dir
                staged_already_failed = True
                logger.exception(
                    "staged restore failed; falling back to %s",
                    self.directory,
                )
        if staging_only:
            # the step exists ONLY in staging and wasn't restorable
            # (stale provenance or a failed read): a fresh job must
            # start from scratch, not crash on a primary that never
            # held this step
            logger.warning(
                "staged step %d not restorable and absent from the "
                "primary; treating as no checkpoint", step,
            )
            return None
        try:
            out = self._restore_from(self.directory, step, abstract_state)
        except Exception:  # noqa: BLE001 — torn/corrupt latest step
            if explicit_step:
                raise
            # before dropping to an older step: the host-DRAM mirror may
            # hold a readable copy of EXACTLY this step (the digest gate
            # above compares against the now-corrupt primary, so it
            # rejected the mirror for the wrong reason). Provenance still
            # must match — a stale mirror from another job must not win.
            if (
                not staged_already_failed
                and self._staging_root is not None
                and self.staged_step() == step
                and self._staging_provenance_valid()
            ):
                try:
                    out = self._restore_from(self._staging_root, step,
                                             abstract_state)
                    logger.warning(
                        "primary step %d unreadable; restored the SAME "
                        "step from host-DRAM staging", step,
                    )
                    self._quarantine_step(step)
                    return out
                except Exception:  # noqa: BLE001 — mirror also bad
                    logger.exception(
                        "staged copy of step %d also unreadable", step)
            # auto-selected latest failed (partial write, bit corruption):
            # a recovering job must come back from the newest GOOD step,
            # not crash on the bad one
            older = sorted(
                (s for s in self._manager.all_steps() if s < step),
                reverse=True,
            )
            logger.exception(
                "restore of latest step %d failed; trying older steps %s",
                step, older,
            )
            for s in older:
                try:
                    out = self._restore_from(self.directory, s,
                                             abstract_state)
                    logger.warning(
                        "restored OLDER checkpoint step=%d (latest %d "
                        "unreadable)", s, step,
                    )
                    self._quarantine_step(step)
                    return out
                except Exception:  # noqa: BLE001 — keep walking back
                    logger.exception("restore of step %d also failed", s)
            raise
        logger.info("restored checkpoint step=%d from %s", step,
                    self.directory)
        return out

    def _quarantine_step(self, step: int) -> None:
        """Move an unreadable step dir aside after a successful fallback.

        Left in place, the corrupt dir keeps winning latest_step() (every
        restart repeats the failed walk) and — worse — Orbax refuses to
        save any step <= the existing latest, so the resumed job's re-save
        at that step number would be silently dropped and progress past
        the fallback step repeatedly lost."""
        src = self._step_dir(self.directory, step)
        dst = os.path.join(self.directory,
                           f"corrupt-{step}-{int(time.time())}")
        try:
            os.replace(src, dst)
            logger.warning("quarantined unreadable step %d -> %s", step, dst)
        except OSError:
            logger.exception("could not quarantine step %d", step)
            return
        try:
            self._manager.reload()  # drop the cached step listing
        except Exception:  # noqa: BLE001 — cache refresh is best-effort
            logger.exception("orbax reload after quarantine failed")

    def _restore_from(
        self, root: str, step: int, abstract_state: Any
    ) -> Dict[str, Any]:
        ocp = self._ocp
        if os.path.abspath(root) == self.directory:
            manager = self._manager
        else:
            manager = ocp.CheckpointManager(
                root,
                options=ocp.CheckpointManagerOptions(
                    enable_async_checkpointing=False, read_only=True,
                ),
            )
        try:
            items = manager.item_metadata(step)
            args = {"state": ocp.args.StandardRestore(abstract_state),
                    "meta": ocp.args.JsonRestore()}
            try:
                has_shards = (
                    items is not None and "data_shards" in items.keys()
                )
            except (AttributeError, TypeError):
                has_shards = False
            if has_shards:
                args["data_shards"] = ocp.args.JsonRestore()
            restored = manager.restore(step, args=ocp.args.Composite(**args))
            out = {
                "state": _rematerialize(restored["state"]),
                "meta": restored["meta"] or {},
                "shard_checkpoint": "",
                "step": step,
            }
            if has_shards and restored.get("data_shards"):
                out["shard_checkpoint"] = restored["data_shards"].get(
                    "checkpoint", ""
                )
            return out
        finally:
            if manager is not self._manager:
                manager.close()

    def close(self):
        self._manager.close()
