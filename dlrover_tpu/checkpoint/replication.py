"""Peer-redundant host snapshots: the checkpoint-free recovery plane.

The HSDP position (PAPERS.md 2602.00277): at pod scale, the dominant
recovery cost is the storage round-trip a *lost* node forces — the
survivors' state is intact (PR 5's live reshard covers them), but the
dead node's shard exists only on disk. This module removes that
round-trip by keeping k in-memory replicas of every node's host-shard
regions in PEER DRAM:

- Each node's :class:`HostSnapshot` is partitioned into deterministic
  per-owner byte regions (``owner_slice``) — the in-memory analogue of
  Universal Checkpointing's sharding-agnostic layout (PAPERS.md
  2406.18820): regions are raw global-array bytes, so the rebuilt host
  tree can be ``device_put`` against *whatever* shardings the survivor
  mesh wants.
- A :class:`SnapshotReplicator` pushes the node's own regions to k
  master-chosen peers on a cadence, off the training thread (the same
  async-staging discipline as ``enable_async_checkpointing``): the
  step path only enqueues; chunking, checksumming and the RPC stream
  run on a background daemon thread.
- Each node serves its :class:`ReplicaStore` over the same two-method
  gRPC surface the master speaks (``rpc.server``), so a rebuilding
  node streams regions straight out of surviving peers' DRAM —
  chunked, length-prefixed, checksummed, with per-chunk retry and a
  mid-transfer-holder-death fallback to the next replica
  (:func:`fetch_tree`). Terminal failure degrades to the Orbax/mirror
  path — graceful degradation is part of the contract.

Wire format (one chunk frame)::

    [4-byte BE header length][header JSON][payload bytes]

Header: ``{"v", "kind": "chunk"|"manifest", "owner", "step", "leaf",
"lo", "hi", "seq", "nbytes", "crc"}`` — ``nbytes`` is the payload
length (the length-prefix integrity check) and ``crc`` its crc32 (the
corruption check the fault-injection matrix flips bytes against). A
snapshot becomes visible to fetchers only once its ``manifest`` frame
commits (per-leaf chunk counts + tree spec + snapshot meta verified),
so a pusher dying mid-transfer leaves no torn state behind.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)

logger = get_logger("checkpoint.replication")

_LEN = struct.Struct(">I")
_WIRE_VERSION = 1


class ChunkCorruptionError(RuntimeError):
    """A chunk frame failed its length-prefix or crc32 check."""


class PeerRestoreError(RuntimeError):
    """No combination of live holders could produce a complete,
    consistent snapshot — callers degrade to the storage path."""


# ---------------------------------------------------------------------------
# region partition + tree spec
# ---------------------------------------------------------------------------


def owner_slice(nbytes: int, group_size: int, owner_rank: int
                ) -> Tuple[int, int]:
    """The contiguous byte range of one leaf that ``owner_rank`` (its
    position in the SORTED owner group) owns. Deterministic and
    boundary-exact: the union over ranks is [0, nbytes) with no overlap
    — what lets a fetcher verify full coverage before trusting a
    rebuild."""
    if group_size <= 0:
        raise ValueError(f"group_size must be positive, got {group_size}")
    if not 0 <= owner_rank < group_size:
        raise ValueError(
            f"owner_rank {owner_rank} outside group of {group_size}")
    lo = (nbytes * owner_rank) // group_size
    hi = (nbytes * (owner_rank + 1)) // group_size
    return lo, hi


def tree_spec(leaves: List[Any]) -> List[Dict[str, Any]]:
    """Per-leaf (dtype, shape) facts of a snapshot's flattened leaves —
    the manifest's structural contract with the rebuilder."""
    spec = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        spec.append({"dtype": arr.dtype.str, "shape": list(arr.shape)})
    return spec


def spec_digest(spec: List[Dict[str, Any]]) -> str:
    """Stable identity of a tree spec: a snapshot replicated for one
    model must never rebuild into another's structure."""
    blob = json.dumps(spec, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# chunk frame codec (length-prefixed + checksummed)
# ---------------------------------------------------------------------------


def _header_blob(fields: Dict[str, Any]) -> bytes:
    return json.dumps(fields, sort_keys=True,
                      separators=(",", ":")).encode()


def encode_chunk(*, kind: str, owner: int, step: int, leaf: int,
                 lo: int, hi: int, seq: int, payload: bytes) -> bytes:
    fields = {
        "v": _WIRE_VERSION, "kind": kind, "owner": int(owner),
        "step": int(step), "leaf": int(leaf), "lo": int(lo),
        "hi": int(hi), "seq": int(seq), "nbytes": len(payload),
        "crc": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    # the payload crc cannot protect the PLACEMENT facts (a flipped
    # lo/hi would write good bytes to the wrong region): the header
    # carries its own crc over the canonical field serialization
    fields["hcrc"] = zlib.crc32(_header_blob(fields)) & 0xFFFFFFFF
    header = _header_blob(fields)
    return b"".join([_LEN.pack(len(header)), header, payload])


def decode_chunk(frame: bytes) -> Tuple[Dict[str, Any], bytes]:
    """Verify the length prefix, the header crc (placement facts) and
    the payload crc32, returning (header, payload). Raises
    :class:`ChunkCorruptionError` on any mismatch — the checksums are
    what turn silent bitrot into a retriable fault."""
    try:
        (hlen,) = _LEN.unpack_from(frame, 0)
        header = json.loads(bytes(frame[4:4 + hlen]))
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        raise ChunkCorruptionError(f"undecodable chunk header: {e}") from e
    try:
        hcrc = int(header.pop("hcrc"))
    except (KeyError, TypeError, ValueError) as e:
        raise ChunkCorruptionError(f"missing header crc: {e}") from e
    if (zlib.crc32(_header_blob(header)) & 0xFFFFFFFF) != hcrc:
        raise ChunkCorruptionError(
            "header crc mismatch: placement facts are untrustworthy")
    payload = bytes(frame[4 + hlen:])
    if len(payload) != int(header.get("nbytes", -1)):
        raise ChunkCorruptionError(
            f"length prefix mismatch: header says {header.get('nbytes')} "
            f"payload bytes, frame carries {len(payload)}")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != int(header.get("crc", -1)):
        raise ChunkCorruptionError(
            f"crc mismatch on owner={header.get('owner')} "
            f"leaf={header.get('leaf')} seq={header.get('seq')}")
    return header, payload


def frame_to_wire(frame: bytes) -> str:
    return base64.b64encode(frame).decode("ascii")


def frame_from_wire(wire: str) -> bytes:
    return base64.b64decode(wire.encode("ascii"))


def build_region_frames(
    *, owner: int, step: int, leaves: List[np.ndarray],
    group: List[int], meta: Dict[str, Any],
    chunk_bytes: int = 256 * 1024,
) -> List[bytes]:
    """Slice ``owner``'s byte regions out of every leaf and frame them:
    N data chunks followed by ONE manifest frame that seals the step.
    ``group`` is the sorted owner set the partition is computed over
    (the snapshot group at push time — recorded in the manifest so a
    fetcher reassembles against the same split even after a resize)."""
    group = sorted(group)
    rank = group.index(owner)
    spec = tree_spec(leaves)
    frames: List[bytes] = []
    manifest_leaves: Dict[str, Dict[str, Any]] = {}
    for idx, leaf in enumerate(leaves):
        arr = np.ascontiguousarray(np.asarray(leaf))
        raw = arr.view(np.uint8).reshape(-1) if arr.ndim else \
            np.frombuffer(arr.tobytes(), dtype=np.uint8)
        lo, hi = owner_slice(arr.nbytes, len(group), rank)
        region = raw[lo:hi].tobytes()
        nchunks = max(1, -(-len(region) // chunk_bytes)) if region else 0
        for seq in range(nchunks):
            piece = region[seq * chunk_bytes:(seq + 1) * chunk_bytes]
            frames.append(encode_chunk(
                kind="chunk", owner=owner, step=step, leaf=idx,
                lo=lo + seq * chunk_bytes,
                hi=lo + seq * chunk_bytes + len(piece),
                seq=seq, payload=piece,
            ))
        manifest_leaves[str(idx)] = {
            "lo": lo, "hi": hi, "nchunks": nchunks,
            "leaf_nbytes": int(arr.nbytes),
        }
    manifest = {
        "owner": owner, "step": step, "group": group,
        "leaves": manifest_leaves, "spec": spec,
        "spec_digest": spec_digest(spec), "meta": dict(meta),
        "pushed_at": time.time(),
    }
    payload = json.dumps(manifest, separators=(",", ":")).encode()
    frames.append(encode_chunk(
        kind="manifest", owner=owner, step=step, leaf=-1, lo=0,
        hi=len(payload), seq=0, payload=payload,
    ))
    return frames


# ---------------------------------------------------------------------------
# the holder side: in-memory store + its RPC servicer
# ---------------------------------------------------------------------------


class ReplicaStore:
    """Per-node in-memory replica store: committed snapshots keyed by
    owner (newest step wins), plus the in-flight staged push. Budget-
    bounded: a chunk that would exceed ``budget_bytes`` is REJECTED
    (the pusher logs a degraded verdict) — a replica plan can degrade,
    it can never OOM this worker."""

    def __init__(self, budget_bytes: int = 0,
                 staged_ttl_secs: float = 600.0,
                 self_owner: Optional[int] = None):
        self._lock = threading.Lock()
        # 0 = uncapped (test/default posture); any positive value is a
        # hard cap on PEER bytes. ``self_owner``'s own regions are
        # exempt: a node must always be able to commit its own
        # snapshot locally (peers rebuild IT from here), whatever DRAM
        # it lends to others.
        self.budget_bytes = int(budget_bytes)
        self._self_owner = self_owner
        # staged cycles older than this are reclaimed: a pusher that
        # died mid-transfer (the exact fault this plane recovers from)
        # must not pin its torn chunks against the budget forever
        self._staged_ttl = float(staged_ttl_secs)
        # owner -> newest-first retained commits, each
        # {"step", "manifest", "chunks": {(leaf, seq): frame}}.
        # TWO-deep retention: during a multi-owner push wave, one
        # owner's fresh commit would otherwise discard the only step
        # every owner still covers — a SIGKILL landing inside that
        # window (the plane's target fault) would force the storage
        # path even though a fully-covered older step existed.
        self._retain_depth = 2
        self._committed: Dict[int, List[Dict[str, Any]]] = {}
        self._staged: Dict[Tuple[int, int], Dict[Tuple[int, int], bytes]] = {}
        # last-touch monotonic time per staged cycle (TTL reclamation)
        self._staged_ts: Dict[Tuple[int, int], float] = {}
        # running resident-byte counter: the budget check must be O(1),
        # not a scan over every frame under the lock per incoming chunk
        self._resident = 0
        reg = get_registry()
        self._g_bytes = reg.gauge(
            tm.REPLICA_STORE_BYTES,
            help="peer-replica bytes resident in this worker's DRAM")
        self._c_corrupt = reg.counter(
            tm.REPLICA_CHUNK_CORRUPTIONS,
            help="chunk frames rejected by the length/crc checks")

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident

    def _drop_staged_locked(self, key: Tuple[int, int]):
        chunks = self._staged.pop(key, None)
        self._staged_ts.pop(key, None)
        if chunks:
            self._resident -= sum(len(f) for f in chunks.values())

    def _reap_stale_staged_locked(self, now: float):
        """Reclaim staged cycles whose pusher went quiet (died
        mid-transfer before its manifest): torn chunks must not pin
        the holder's replica budget forever."""
        for key in [k for k, ts in self._staged_ts.items()
                    if now - ts > self._staged_ttl]:
            logger.warning(
                "reclaiming staged replica cycle owner=%d step=%d: no "
                "manifest within %.0fs (pusher died mid-transfer?)",
                key[0], key[1], self._staged_ttl)
            self._drop_staged_locked(key)

    def put_frame(self, frame: bytes) -> Tuple[bool, str]:
        """Ingest one frame. Data chunks stage; the manifest frame
        verifies coverage and commits (superseding any older committed
        step for that owner). Returns (ok, reason)."""
        try:
            header, payload = decode_chunk(frame)
        except ChunkCorruptionError as e:
            self._c_corrupt.inc()
            logger.warning("[REPLICA_CORRUPT] rejected chunk on put: %s", e)
            return False, f"corrupt: {e}"
        owner, step = int(header["owner"]), int(header["step"])
        now = time.monotonic()
        with self._lock:
            self._reap_stale_staged_locked(now)
            if header["kind"] == "chunk":
                if (
                    self.budget_bytes
                    and owner != self._self_owner
                    and self._resident + len(frame) > self.budget_bytes
                ):
                    return False, "budget"
                staged = self._staged.setdefault((owner, step), {})
                key = (int(header["leaf"]), int(header["seq"]))
                prev = staged.get(key)
                if prev is not None:
                    self._resident -= len(prev)  # idempotent re-put
                staged[key] = bytes(frame)
                self._resident += len(frame)
                self._staged_ts[(owner, step)] = now
                return True, ""
            # manifest: verify every listed chunk is staged, then commit
            manifest = json.loads(payload)
            staged = self._staged.get((owner, step), {})
            for leaf_key, info in manifest["leaves"].items():
                leaf = int(leaf_key)
                for seq in range(int(info["nchunks"])):
                    if (leaf, seq) not in staged:
                        return False, (
                            f"incomplete: leaf {leaf} chunk {seq} missing"
                        )
            entries = self._committed.setdefault(owner, [])
            if entries and int(entries[0]["step"]) > step:
                # a stale push (slow retry of an old cycle) must not
                # roll a fresher committed snapshot back
                self._drop_staged_locked((owner, step))
                return False, "stale"
            if entries and int(entries[0]["step"]) == step:
                # idempotent re-commit of the same step: replace
                self._resident -= sum(
                    len(f) for f in entries[0]["chunks"].values())
                entries.pop(0)
            entries.insert(0, {
                "step": step, "manifest": manifest, "chunks": staged,
            })
            while len(entries) > self._retain_depth:
                evicted = entries.pop()
                self._resident -= sum(
                    len(f) for f in evicted["chunks"].values())
            # the staged bytes are now committed bytes: only the
            # bookkeeping moves, the counter already holds them
            self._staged.pop((owner, step), None)
            self._staged_ts.pop((owner, step), None)
            # drop any older staged cycles of this owner too
            for key in [k for k in self._staged if k[0] == owner
                        and k[1] < step]:
                self._drop_staged_locked(key)
            self._g_bytes.set(self._resident)
        return True, ""

    def fetch(self, owner: int, step: int, leaf: int, seq: int
              ) -> Optional[bytes]:
        with self._lock:
            for entry in self._committed.get(owner, []):
                if int(entry["step"]) == step:
                    return entry["chunks"].get((leaf, seq))
            return None

    def inventory(self, owner: int = -1) -> Dict[str, Any]:
        """Committed holdings: {owner: {"step", "manifest", "steps"}} —
        "step"/"manifest" are the NEWEST retained commit, "steps" maps
        every retained step to its manifest (the fetcher's
        best_common_step sweeps all of them). Chunks are elided — the
        fetcher pulls them one at a time."""
        with self._lock:
            out = {}
            for o, entries in self._committed.items():
                if owner >= 0 and o != owner or not entries:
                    continue
                out[str(o)] = {
                    "step": int(entries[0]["step"]),
                    "manifest": entries[0]["manifest"],
                    "steps": {
                        str(e["step"]): e["manifest"] for e in entries
                    },
                }
            return out

    def drop_owner(self, owner: int):
        with self._lock:
            for entry in self._committed.pop(owner, []):
                self._resident -= sum(
                    len(f) for f in entry["chunks"].values())
            for key in [k for k in self._staged if k[0] == owner]:
                self._drop_staged_locked(key)
            self._g_bytes.set(self._resident)


class ReplicaServicer:
    """The two-method (get/report) servicer fronting a ReplicaStore —
    served by ``rpc.server.build_server`` exactly like the master, so
    peers speak the surface that already exists."""

    def __init__(self, store: ReplicaStore):
        self.store = store

    def report(self, request, context=None):
        from dlrover_tpu.common import comm

        if isinstance(request, comm.ReplicaPut):
            ok, reason = self.store.put_frame(
                frame_from_wire(request.frame))
            return comm.Response(success=ok, reason=reason)
        return comm.Response(
            success=False,
            reason=f"no replica report handler: {type(request).__name__}",
        )

    def get(self, request, context=None):
        from dlrover_tpu.common import comm

        if isinstance(request, comm.ReplicaFetchRequest):
            frame = self.store.fetch(
                request.owner, request.step, request.leaf, request.seq)
            if frame is None:
                return comm.ReplicaFrame(frame="", found=False)
            return comm.ReplicaFrame(
                frame=frame_to_wire(frame), found=True)
        if isinstance(request, comm.ReplicaInfoRequest):
            return comm.DiagnosisReport(report_json=json.dumps(
                self.store.inventory(request.owner)))
        return comm.Response(
            success=False,
            reason=f"no replica get handler: {type(request).__name__}",
        )


def start_replica_server(store: ReplicaStore, port: int = 0,
                         host: str = "0.0.0.0"):
    """Serve a ReplicaStore; returns (server, bound_port)."""
    from dlrover_tpu.rpc.server import build_server

    server, bound = build_server(ReplicaServicer(store), port=port,
                                 host=host)
    server.start()
    return server, bound


# ---------------------------------------------------------------------------
# the pusher side
# ---------------------------------------------------------------------------


def default_replica_budget_bytes() -> int:
    """The host-DRAM budget this node grants to peer replicas: the
    configured ``replica_budget_mb`` capped by a quarter of the host's
    available memory right now — the same host-accounting posture the
    PR 8 plane reports (``rss_mb`` / headroom gauges), so an admission
    decision never prices against memory the training process is about
    to need. A NEGATIVE knob means "lend no DRAM to peers" (the store
    still commits this node's OWN regions — self regions are budget-
    exempt); 0 means uncapped."""
    from dlrover_tpu.common.config import get_context

    mb = float(get_context().replica_budget_mb)
    if mb < 0:
        return 1  # effectively nothing: every peer chunk is refused
    if mb == 0:
        return 0  # uncapped
    budget = int(mb * 1024 * 1024)
    try:
        import psutil

        avail = int(psutil.virtual_memory().available)
        budget = min(budget, avail // 4)
    except Exception as e:  # noqa: BLE001 — psutil-less hosts keep the knob
        logger.debug("psutil unavailable for budget sizing (%s: %s)",
                     type(e).__name__, e)
    return max(budget, 1)


class SnapshotReplicator:
    """Owns this node's replica store + server, registers the endpoint
    with the master, and pushes the node's own snapshot regions to the
    master-assigned peers on demand.

    ``submit()`` is the only step-path entry: it enqueues (bounded,
    drop-on-backpressure — replication must never stall the loop) and
    the daemon sender thread does the slicing, framing, local commit
    and per-peer RPC stream. Peer channels are ``RpcChannel``s, so
    every chunk rides the hardened transient-retry path (jittered
    exponential backoff); a peer that stays down is dropped for the
    cycle with a counted, error-coded event — degradation, not a
    crash."""

    def __init__(self, master_client, node_id: int,
                 port: int = 0, budget_bytes: Optional[int] = None,
                 chunk_bytes: Optional[int] = None,
                 advertise_host: str = "127.0.0.1"):
        import queue

        from dlrover_tpu.common.config import get_context

        ctx = get_context()
        self._client = master_client
        self.node_id = int(node_id)
        if budget_bytes is None:
            budget_bytes = default_replica_budget_bytes()
        self.store = ReplicaStore(budget_bytes=budget_bytes,
                                  self_owner=self.node_id)
        self._server, self._port = start_replica_server(
            self.store, port=port or int(getattr(ctx, "replica_port", 0)))
        self.addr = f"{advertise_host}:{self._port}"
        self._chunk_bytes = int(
            chunk_bytes if chunk_bytes is not None
            else float(getattr(ctx, "replica_chunk_kb", 256)) * 1024)
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._sender: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._channel, self._close_channels = replica_channel_factory()
        self.last_pushed_step = -1
        self.last_plan: Optional[Dict[str, Any]] = None
        # last completed cycle's wall/bytes, re-reported with every
        # endpoint registration: the master's readiness auditor
        # calibrates the rebuild transfer term from them (a push
        # streams the same bytes a rebuild fetches back)
        self.last_push_seconds = 0.0
        self.last_push_bytes = 0.0
        # maintenance/chaos pause: submissions are dropped (counted)
        # while True — the "expired cadence" failure mode on demand
        self.paused = False
        reg = get_registry()
        self._c_pushes = reg.counter(
            tm.REPLICA_PUSHES,
            help="snapshot replication cycles completed")
        self._c_push_failures = reg.counter(
            tm.REPLICA_PUSH_FAILURES,
            help="peer pushes dropped (dead peer / budget / backpressure)")
        self._c_bytes = reg.counter(
            tm.REPLICA_BYTES_PUSHED,
            help="region bytes shipped to peer stores")
        self._h_push = reg.histogram(
            tm.REPLICA_PUSH_TIME,
            help="one replication cycle: slice + frame + peer stream")
        self._register_endpoint(snapshot_mb=0.0)

    @property
    def port(self) -> int:
        return self._port

    @property
    def plan_cadence_steps(self) -> int:
        """The MASTER-computed cluster-wide cadence from the last plan
        (0 = none yet): when present, the replica hook paces by it
        INSTEAD of the local wall floor, so every node pushes at the
        same global-step multiples and a rebuild always finds one step
        with full owner coverage."""
        return int((self.last_plan or {}).get("cadence_steps", 0) or 0)

    def _register_endpoint(self, snapshot_mb: float):
        try:
            self._client.report_replica_endpoint(
                node_id=self.node_id, addr=self.addr,
                budget_mb=self.store.budget_bytes / (1024 * 1024),
                snapshot_mb=float(snapshot_mb),
                step=int(self.last_pushed_step),
                push_seconds=float(self.last_push_seconds),
                push_bytes=float(self.last_push_bytes),
            )
        except Exception as e:  # noqa: BLE001 — a briefly-away master
            # only delays the plan; the next cycle re-registers
            logger.warning("replica endpoint registration failed "
                           "(%s: %s)", type(e).__name__, e)

    # -- step-path entry -----------------------------------------------------

    def submit(self, tree: Any, meta: Dict[str, Any], step: int) -> bool:
        """Enqueue one snapshot tree for replication. Returns False when
        the previous cycle is still in flight (dropped — the next
        cadence's fresher snapshot supersedes this one)."""
        import queue

        if self.paused:
            self._c_push_failures.inc()
            return False
        if self._sender is None or not self._sender.is_alive():
            self._sender = threading.Thread(
                target=self._send_loop, name="snapshot-replicator",
                daemon=True)
            self._sender.start()
        try:
            self._queue.put_nowait((tree, dict(meta), int(step)))
            return True
        except queue.Full:
            self._c_push_failures.inc()
            return False

    # -- the background cycle ------------------------------------------------

    def _send_loop(self):
        while not self._stop.is_set():
            item = self._queue.get()
            if item is None:
                return
            try:
                self._replicate_once(*item)
            except Exception:  # noqa: BLE001 — replication is redundancy,
                # never a reason to kill the worker
                self._c_push_failures.inc()
                logger.exception("replication cycle failed")

    def _replicate_once(self, tree: Any, meta: Dict[str, Any], step: int):
        import jax

        t0 = time.monotonic()
        leaves = [np.asarray(x) for x in jax.tree.leaves(tree)]
        nbytes = sum(x.nbytes for x in leaves)
        self._register_endpoint(snapshot_mb=nbytes / (1024 * 1024))
        plan = self._fetch_plan()
        group = sorted(set(
            [self.node_id] + [int(p["node_id"])
                              for p in (plan or {}).get("peers", [])]
        )) if plan else [self.node_id]
        if plan and plan.get("group"):
            group = sorted(int(g) for g in plan["group"])
        frames = build_region_frames(
            owner=self.node_id, step=step, leaves=leaves, group=group,
            meta=meta, chunk_bytes=self._chunk_bytes,
        )
        # local commit first: this node is always holder #0 of its own
        # regions (peers of a DIFFERENT lost node fetch them from here)
        for frame in frames:
            ok, reason = self.store.put_frame(frame)
            if not ok:
                logger.warning("local replica commit refused: %s", reason)
        pushed_peers = []
        for peer in (plan or {}).get("peers", []):
            addr = peer.get("addr", "")
            if not addr:
                continue
            if self._push_to_peer(addr, frames):
                pushed_peers.append(int(peer.get("node_id", -1)))
        self.last_pushed_step = step
        push_s = time.monotonic() - t0
        # bytes per PEER-stream: the calibration wants the one-holder
        # transfer a rebuild fetch would repeat, so a k-peer cycle's
        # wall is paired with a single peer's worth of frame bytes
        frame_bytes = sum(len(f) for f in frames)
        if pushed_peers and frame_bytes > 0:
            self.last_push_seconds = push_s
            self.last_push_bytes = float(frame_bytes)
        self._register_endpoint(snapshot_mb=nbytes / (1024 * 1024))
        self._c_pushes.inc()
        self._h_push.observe(push_s)
        # bytes actually SHIPPED: zero peers reached = zero bytes (a
        # counter that kept rising while nothing left the host would
        # mask a total redundancy outage on dashboards)
        region_bytes = sum(
            len(f) for f in frames) * len(pushed_peers)
        self._c_bytes.inc(region_bytes)
        emit_event(EventKind.REPLICA_PUSHED, step=step,
                   peers=pushed_peers, bytes=region_bytes,
                   push_seconds=round(push_s, 3),
                   replicas=len(pushed_peers),
                   degraded=bool((plan or {}).get("degraded", False)))

    def _fetch_plan(self) -> Optional[Dict[str, Any]]:
        try:
            plan = self._client.get_replica_plan()
        except Exception as e:  # noqa: BLE001 — a master blip skips one
            # cycle; the local commit still lands
            logger.warning("replica plan fetch failed (%s: %s)",
                           type(e).__name__, e)
            return self.last_plan
        if plan is None:
            return self.last_plan
        out = {
            "peers": list(plan.peers or []),
            "replicas": int(plan.replicas),
            "requested": int(plan.requested),
            "group": [int(g) for g in (plan.group or [])],
            "cadence_steps": int(getattr(plan, "cadence_steps", 0) or 0),
            "degraded": bool(plan.degraded),
            "reason": plan.reason or "",
        }
        if plan.degraded and (
            self.last_plan is None
            or not self.last_plan.get("degraded")
        ):
            emit_event(EventKind.REPLICA_PLAN_DEGRADED,
                       error_code="REPLICA_BUDGET",
                       replicas=out["replicas"],
                       requested=out["requested"],
                       reason=out["reason"])
        self.last_plan = out
        return out

    def _push_to_peer(self, addr: str, frames: List[bytes]) -> bool:
        from dlrover_tpu.common import comm

        channel = self._channel(addr)
        for frame in frames:
            try:
                resp = channel.report(comm.ReplicaPut(
                    node_id=self.node_id, frame=frame_to_wire(frame)))
            except Exception as e:  # noqa: BLE001 — the channel already
                # retried transients; a peer that stays down degrades
                # THIS cycle's redundancy, it does not fail the worker
                self._c_push_failures.inc()
                logger.warning(
                    "[REPLICA_PEER_DOWN] push to peer %s failed; this "
                    "cycle ships one replica fewer (%s: %s)",
                    addr, type(e).__name__, e)
                emit_event(EventKind.REPLICA_PUSH_FAILED,
                           error_code="REPLICA_PEER_DOWN", peer=addr,
                           detail=f"{type(e).__name__}: {e}"[:200])
                return False
            if not resp.success:
                self._c_push_failures.inc()
                code = ("REPLICA_BUDGET" if resp.reason == "budget"
                        else "REPLICA_PUT_REFUSED")
                emit_event(EventKind.REPLICA_PUSH_FAILED,
                           error_code=code, peer=addr,
                           detail=resp.reason[:200])
                return False
        return True

    def stop(self):
        self._stop.set()
        try:
            self._queue.put_nowait(None)
        except Exception:  # noqa: BLE001 — full queue: sender mid-cycle
            logger.debug("replicator queue full at stop", exc_info=True)
        if self._sender is not None:
            self._sender.join(timeout=5.0)
        self._close_channels()
        self._server.stop(grace=0.5)


# ---------------------------------------------------------------------------
# the fetch side: peer rebuild
# ---------------------------------------------------------------------------


def _collect_inventories(endpoints: List[Dict[str, Any]],
                         channel_factory) -> Dict[str, Dict[str, Any]]:
    """addr -> inventory for every reachable endpoint (dead holders are
    skipped, not fatal — fallback is the whole point). An address that
    failed once is never re-dialed: recovery plans list the full HRW
    ranking per owner, so one unreachable endpoint would otherwise pay
    its channel timeout once per OWNER, serially — minutes of pure
    timeout before any chunk moves."""
    from dlrover_tpu.common import comm

    out: Dict[str, Dict[str, Any]] = {}
    failed: set = set()
    for ep in endpoints:
        addr = ep.get("addr", "")
        if not addr or addr in out or addr in failed:
            continue
        try:
            resp = channel_factory(addr).get(comm.ReplicaInfoRequest())
            out[addr] = json.loads(resp.report_json or "{}")
        except Exception as e:  # noqa: BLE001 — unreachable holder
            failed.add(addr)
            logger.warning("replica inventory fetch from %s failed "
                           "(%s: %s)", addr, type(e).__name__, e)
    return out


def best_common_step(inventories: Dict[str, Dict[str, Any]]
                     ) -> Optional[Tuple[int, List[int]]]:
    """The highest step at which every owner of that step's snapshot
    group has a committed manifest on SOME reachable holder. Returns
    (step, sorted owner group) or None."""
    # step -> owner -> manifest (sweeping EVERY retained step per
    # owner, not just the newest: mid-push-wave the newest steps are
    # partially covered and the fully-covered step is the older one)
    by_step: Dict[int, Dict[int, Dict[str, Any]]] = {}
    for inv in inventories.values():
        for owner_key, entry in inv.items():
            steps = entry.get("steps") or {
                str(entry["step"]): entry["manifest"]}
            for step_key, manifest in steps.items():
                by_step.setdefault(int(step_key), {})[
                    int(owner_key)] = manifest
    for step in sorted(by_step, reverse=True):
        owners = by_step[step]
        groups = {tuple(m.get("group", [])) for m in owners.values()}
        if len(groups) != 1:
            continue
        group = sorted(next(iter(groups)))
        if set(owners) == set(group):
            return step, group
    return None


def fetch_tree(
    abstract_leaves: List[Any],
    holders_by_owner: Dict[int, List[Dict[str, Any]]],
    channel_factory,
    expected_digest: Optional[str] = None,
    inventories: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Tuple[List[np.ndarray], Dict[str, Any], int, int]:
    """Stream every owner's regions out of its live holders and
    reassemble the full host tree.

    Per owner: holders are tried in plan order; chunks stream one RPC
    at a time (each already carrying the transient-retry channel), a
    corrupt chunk is re-fetched once and then the next holder takes
    over; a holder whose CHANNEL dies is marked dead for the rest of
    the fetch (resuming mid-transfer on the next replica — chunks are
    identical across holders by construction, and later chunks must
    not re-pay the dead holder's timeout). An owner none of whose
    holders can produce a complete, checksummed region set raises
    :class:`PeerRestoreError` (the caller's storage fallback).

    ``inventories``: a pre-collected holder-inventory sweep (see
    :func:`_collect_inventories` / :func:`best_common_step`) — callers
    that already peeked the candidate step to run a cheap staleness
    gate pass it in so the sweep is not paid twice.

    Returns (leaves, snapshot meta, step, bytes_fetched_over_wire).
    """
    from dlrover_tpu.common import comm

    reg = get_registry()
    c_retries = reg.counter(
        tm.REPLICA_FETCH_RETRIES,
        help="chunk fetches retried or failed over to the next holder")
    c_corrupt = reg.counter(tm.REPLICA_CHUNK_CORRUPTIONS)
    if inventories is None:
        all_endpoints = [
            ep for eps in holders_by_owner.values() for ep in eps]
        inventories = _collect_inventories(all_endpoints, channel_factory)
    found = best_common_step(inventories)
    if found is None:
        raise PeerRestoreError(
            "no step with full owner coverage on any reachable holder")
    step, group = found
    dead_holders: set = set()
    spec = [{"dtype": np.asarray(x).dtype.str
             if not hasattr(x, "dtype") else np.dtype(x.dtype).str,
             "shape": list(x.shape)} for x in abstract_leaves]
    digest = expected_digest or spec_digest(spec)
    buffers = [np.zeros(int(np.prod(s["shape"] or [1]))
                        * np.dtype(s["dtype"]).itemsize, dtype=np.uint8)
               for s in spec]
    covered = [0 for _ in spec]
    meta: Dict[str, Any] = {}
    wire_bytes = 0

    for owner in group:
        candidates = [ep for ep in holders_by_owner.get(owner, [])
                      if ep.get("addr") in inventories
                      and str(owner) in inventories[ep["addr"]]
                      and int(inventories[ep["addr"]][str(owner)]["step"])
                      == step]
        if not candidates:
            raise PeerRestoreError(
                f"owner {owner}: no live holder carries step {step}")
        manifest = inventories[candidates[0]["addr"]][str(owner)][
            "manifest"]
        if manifest.get("spec_digest") != digest:
            raise PeerRestoreError(
                f"owner {owner}: snapshot structure "
                f"{manifest.get('spec_digest')} does not match this "
                f"trainer's {digest}")
        if int(owner) == min(group) or not meta:
            meta = dict(manifest.get("meta", {}))
        for leaf_key, info in manifest["leaves"].items():
            leaf = int(leaf_key)
            for seq in range(int(info["nchunks"])):
                payload = None
                for ep in candidates:
                    addr = ep["addr"]
                    if addr in dead_holders:
                        continue
                    attempts = 0
                    while attempts < 2 and payload is None:
                        attempts += 1
                        try:
                            resp = channel_factory(addr).get(
                                comm.ReplicaFetchRequest(
                                    owner=owner, step=step,
                                    leaf=leaf, seq=seq))
                        except Exception as e:  # noqa: BLE001 — holder
                            # died mid-transfer: fall to the next
                            # replica, and never come back to this one
                            # (each visit re-pays the channel timeout)
                            dead_holders.add(addr)
                            c_retries.inc()
                            logger.warning(
                                "[REPLICA_HOLDER_LOST] holder %s died "
                                "mid-transfer (owner %d leaf %d chunk "
                                "%d); falling to the next replica "
                                "(%s: %s)", addr, owner, leaf, seq,
                                type(e).__name__, e)
                            emit_event(
                                EventKind.REPLICA_HOLDER_LOST,
                                error_code="REPLICA_HOLDER_LOST",
                                holder=addr, owner=owner, leaf=leaf,
                                seq=seq,
                                detail=f"{type(e).__name__}"[:80])
                            break
                        if not getattr(resp, "found", False):
                            c_retries.inc()
                            break
                        raw = frame_from_wire(resp.frame)
                        try:
                            header, data = decode_chunk(raw)
                            # the crc covers only the PAYLOAD — a bit
                            # flip inside the JSON header can still
                            # parse. Validate the placement facts
                            # before trusting them with a buffer
                            # write: identity, bounds, and the
                            # length/offset consistency.
                            lo = int(header["lo"])
                            hi = int(header["hi"])
                            leaf_nbytes = len(buffers[leaf])
                            if (int(header["owner"]) != owner
                                    or int(header["leaf"]) != leaf
                                    or int(header["seq"]) != seq
                                    or not 0 <= lo <= hi <= leaf_nbytes
                                    or hi - lo != len(data)):
                                raise ChunkCorruptionError(
                                    f"header placement invalid: "
                                    f"owner={header.get('owner')} "
                                    f"leaf={header.get('leaf')} "
                                    f"seq={header.get('seq')} "
                                    f"lo={lo} hi={hi} "
                                    f"payload={len(data)}")
                        except ChunkCorruptionError as e:
                            c_corrupt.inc()
                            c_retries.inc()
                            logger.warning(
                                "[REPLICA_CORRUPT] chunk from %s "
                                "failed validation (attempt %d): %s",
                                addr, attempts, e)
                            continue  # retry the same holder once
                        payload = (lo, hi, data)
                        # bytes of the DECODED frame: the base64 wire
                        # inflation must not pollute the MTTR-vs-bytes
                        # accounting this counter feeds
                        wire_bytes += len(raw)
                    if payload is not None:
                        break
                if payload is None:
                    raise PeerRestoreError(
                        f"owner {owner} leaf {leaf} chunk {seq}: "
                        f"exhausted every holder")
                lo, hi, data = payload
                buffers[leaf][lo:hi] = np.frombuffer(data, dtype=np.uint8)
                covered[leaf] += hi - lo

    leaves = []
    for idx, s in enumerate(spec):
        expected = int(np.prod(s["shape"] or [1])) * np.dtype(
            s["dtype"]).itemsize
        if covered[idx] != expected:
            raise PeerRestoreError(
                f"leaf {idx}: fetched {covered[idx]} of {expected} "
                f"bytes — region coverage incomplete")
        # copy-free: buffers[idx] is a fresh contiguous uint8 array we
        # own outright — a dtype view avoids transiently doubling host
        # memory per leaf on an already-pressured recovering node
        arr = buffers[idx].view(np.dtype(s["dtype"]))
        leaves.append(arr.reshape(s["shape"]))
    return leaves, meta, step, wire_bytes


def replica_channel_factory():
    """The ONE fast-fail channel policy for the replica plane (push and
    fetch sides share it): a dead peer/holder must cost milliseconds,
    not the patient master-channel backoff ladder. Returns a caching
    ``factory(addr) -> RpcChannel`` plus a ``close()`` that tears the
    cache down."""
    from dlrover_tpu.rpc.client import RpcChannel

    channels: Dict[str, Any] = {}

    def factory(addr: str):
        ch = channels.get(addr)
        if ch is None:
            ch = RpcChannel(addr, timeout=10.0, retries=2, backoff=0.2)
            channels[addr] = ch
        return ch

    def close():
        for ch in channels.values():
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                logger.debug("replica channel close failed",
                             exc_info=True)
        channels.clear()

    return factory, close
