"""Meta-device initialization: build giant models without host OOM.

Role parity: ``atorch/atorch/utils/meta_model_utils.py:650``
(``reload_meta_module`` — init on the meta device, materialize weights
on demand) and ``meta_overrides.py`` (meta kernels for shape inference).
The JAX shape: ``jax.eval_shape`` IS the meta device — an abstract init
costs nothing; materialization happens directly into the target
``NamedSharding``s so a 100B parameter tree never exists unsharded or
on one host.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger("utils.meta_init")


def abstract_init(init_fn: Callable, rng: Optional[jax.Array] = None) -> Any:
    """Trace ``init_fn`` without allocating: a ShapeDtypeStruct pytree
    (the "meta model")."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return jax.eval_shape(init_fn, rng)


def param_stats(abstract: Any) -> Dict[str, float]:
    """{"params": N, "bytes": B} from a meta tree (reference: meta-based
    FLOPs/size accounting)."""
    leaves = jax.tree.leaves(abstract)
    params = sum(math.prod(map(int, leaf.shape)) for leaf in leaves)
    nbytes = sum(
        math.prod(map(int, leaf.shape)) * np.dtype(leaf.dtype).itemsize
        for leaf in leaves
    )
    return {"params": params, "bytes": nbytes}


def materialize_sharded(
    init_fn: Callable,
    shardings: Any,
    rng: Optional[jax.Array] = None,
) -> Any:
    """Run init under jit with output shardings: every weight is created
    directly in its mesh placement (per-device shards only; the full
    tensor never exists on the host)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return jax.jit(init_fn, out_shardings=shardings)(rng)


def materialize_leaf_by_leaf(
    abstract: Any,
    leaf_init: Callable[[jax.Array, Any], jax.Array],
    shardings: Any = None,
    rng: Optional[jax.Array] = None,
) -> Any:
    """Materialize one leaf at a time (the reference's
    materialize-on-demand loop): peak host/device scratch is one leaf,
    not the whole tree. ``leaf_init(rng, shape_dtype) -> array``."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    leaves, treedef = jax.tree.flatten(abstract)
    shard_leaves = (
        jax.tree.leaves(shardings) if shardings is not None
        else [None] * len(leaves)
    )
    if len(shard_leaves) != len(leaves):
        raise ValueError(
            f"shardings tree has {len(shard_leaves)} leaves, abstract has "
            f"{len(leaves)}"
        )
    rngs = jax.random.split(rng, max(len(leaves), 1))
    out = []
    for leaf_rng, leaf, sharding in zip(rngs, leaves, shard_leaves):
        if sharding is not None:
            made = jax.jit(
                lambda r, leaf=leaf: leaf_init(r, leaf),
                out_shardings=sharding,
            )(leaf_rng)
        else:
            made = leaf_init(leaf_rng, leaf)
        out.append(made)
    return jax.tree.unflatten(treedef, out)


def default_leaf_init(rng: jax.Array, leaf: Any) -> jax.Array:
    """Fan-in-scaled normal for matrices, zeros for vectors — a usable
    stand-in when the real initializer is too entangled to call
    per-leaf."""
    import jax.numpy as jnp

    shape = tuple(int(s) for s in leaf.shape)
    if len(shape) < 2:
        return jnp.zeros(shape, leaf.dtype)
    scale = 1.0 / math.sqrt(shape[-2])
    return (jax.random.normal(rng, shape, jnp.float32) * scale).astype(
        leaf.dtype
    )


def materialize_from_checkpoint(
    ckpt_manager,
    abstract: Any,
    shardings: Any = None,
) -> Optional[Any]:
    """Restore a meta tree straight into its shardings (the reference's
    reshard-on-load ``fsdp_save_util`` path; Orbax does the resharding).
    Returns None when no checkpoint exists."""
    from dlrover_tpu.checkpoint.manager import abstract_like

    target = abstract_like(abstract, shardings)
    out = ckpt_manager.restore(target)
    if out is None:
        return None
    return out["state"]
