"""FLOPs / memory / latency profiling.

Role parity: ``atorch/atorch/utils/prof.py:41`` (``AProfiler`` — per-module
FLOPs/params/latency via forward hooks and hand-written per-op formulas,
``:486-692``) and ``auto/dry_runner/dry_runner.py:12-144`` (timed dryrun
steps feeding the strategy search).

TPU-first: no hooks and no hand-written formulas — XLA already knows. A
jitted function's ``compiled.cost_analysis()`` carries exact FLOPs and
bytes-accessed for the whole fused program, and ``memory_analysis()`` the
real HBM footprint after layout/fusion. The dry runner times the compiled
step on device, which is what the auto-tune search actually optimizes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger("utils.prof")


def count_params(tree: Any) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def param_bytes(tree: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


@dataclass
class CostReport:
    flops: float = 0.0
    bytes_accessed: float = 0.0
    peak_memory_bytes: int = 0
    # arithmetic intensity = flops / bytes: low values ⇒ HBM-bound on TPU.
    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0


def analyze_cost(fn: Callable, *args, **kwargs) -> CostReport:
    """Compile ``fn`` for the given args and read XLA's cost model."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # old jax: one dict per program
        cost = cost[0] if cost else {}
    report = CostReport(
        flops=float(cost.get("flops", 0.0)),
        bytes_accessed=float(cost.get("bytes accessed", 0.0)),
    )
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            report.peak_memory_bytes = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
            )
    except Exception:  # noqa: BLE001 - backend-dependent API
        pass
    return report


@dataclass
class ProfileResult:
    steps_per_sec: float
    step_time_ms: float
    flops_per_step: float
    achieved_flops_per_sec: float
    param_count: int
    peak_memory_bytes: int

    def mfu(self, peak_flops_per_sec: float) -> float:
        """Model FLOPs utilization against a hardware peak."""
        if peak_flops_per_sec <= 0:
            return 0.0
        return self.achieved_flops_per_sec / peak_flops_per_sec


class DryRunner:
    """Timed execution of a compiled train step (reference: dry_runner).

    Env knobs mirror the reference's
    ``ATORCH_DRYRUN_WARMUP_STEP``/``PROFILE_STEP``
    (``auto/accelerate.py:150-152``):
    ``DLROVER_TPU_DRYRUN_WARMUP`` / ``DLROVER_TPU_DRYRUN_STEPS``.
    """

    def __init__(self, warmup: Optional[int] = None, steps: Optional[int] = None):
        import os

        self.warmup = warmup if warmup is not None else int(
            os.environ.get("DLROVER_TPU_DRYRUN_WARMUP", "2")
        )
        self.steps = steps if steps is not None else int(
            os.environ.get("DLROVER_TPU_DRYRUN_STEPS", "5")
        )

    def profile(
        self,
        train_step: Callable,
        state: Any,
        batch: Any,
        rng: Optional[jax.Array] = None,
    ) -> ProfileResult:
        """Run warmup + timed steps; returns throughput + cost facts.

        ``train_step`` must be (state, batch, rng) -> (state, metrics) and
        already sharded/jitted (i.e. ``AccelerateResult.train_step``).
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        cost = analyze_cost(train_step, state, batch, rng)

        for _ in range(max(self.warmup, 1)):
            state, _ = train_step(state, batch, rng)
        jax.block_until_ready(state)

        t0 = time.perf_counter()
        for _ in range(max(self.steps, 1)):
            state, metrics = train_step(state, batch, rng)
        jax.block_until_ready(state)
        elapsed = time.perf_counter() - t0

        n = max(self.steps, 1)
        sps = n / elapsed
        result = ProfileResult(
            steps_per_sec=sps,
            step_time_ms=1000.0 * elapsed / n,
            flops_per_step=cost.flops,
            achieved_flops_per_sec=cost.flops * sps,
            param_count=count_params(state.params)
            if hasattr(state, "params") else count_params(state),
            peak_memory_bytes=cost.peak_memory_bytes,
        )
        logger.info(
            "dryrun: %.2f steps/s (%.1f ms/step), %.3g flops/step, "
            "%d params",
            result.steps_per_sec, result.step_time_ms,
            result.flops_per_step, result.param_count,
        )
        return result


class AProfiler:
    """Model-level profile summary (reference: AProfiler).

    Where the reference walks modules with hooks, here the unit of
    reporting is the pytree path: per-subtree parameter counts plus the
    whole-program XLA cost — per-op FLOPs formulas are obsolete under
    fusion, so they are intentionally not reproduced.
    """

    def __init__(self, params: Any):
        self._params = params

    def params_by_subtree(self, depth: int = 1) -> Dict[str, int]:
        out: Dict[str, int] = {}
        flat = jax.tree_util.tree_flatten_with_path(self._params)[0]
        for path, leaf in flat:
            key = "/".join(
                str(getattr(p, "key", getattr(p, "idx", p)))
                for p in path[:depth]
            )
            out[key] = out.get(key, 0) + leaf.size
        return out

    def summary(
        self, loss_fn: Optional[Callable] = None, batch: Any = None,
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, Any]:
        info: Dict[str, Any] = {
            "param_count": count_params(self._params),
            "param_bytes": param_bytes(self._params),
            "subtrees": self.params_by_subtree(),
        }
        if loss_fn is not None and batch is not None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            cost = analyze_cost(loss_fn, self._params, batch, rng)
            info["forward_flops"] = cost.flops
            info["bytes_accessed"] = cost.bytes_accessed
            info["arithmetic_intensity"] = cost.arithmetic_intensity
        return info
