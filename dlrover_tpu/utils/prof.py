"""FLOPs / memory / latency profiling.

Role parity: ``atorch/atorch/utils/prof.py:41`` (``AProfiler`` — per-module
FLOPs/params/latency via forward hooks and hand-written per-op formulas,
``:486-692``) and ``auto/dry_runner/dry_runner.py:12-144`` (timed dryrun
steps feeding the strategy search).

TPU-first: no hooks and no hand-written formulas — XLA already knows. A
jitted function's ``compiled.cost_analysis()`` carries exact FLOPs and
bytes-accessed for the whole fused program, and ``memory_analysis()`` the
real HBM footprint after layout/fusion. The dry runner times the compiled
step on device, which is what the auto-tune search actually optimizes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from dlrover_tpu.common.log import get_logger

logger = get_logger("utils.prof")


def count_params(tree: Any) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def param_bytes(tree: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def cost_analysis_dict(compiled) -> Dict[str, float]:
    """``compiled.cost_analysis()`` as ONE dict, shimming the legacy-jax
    shape (old jax returns a list with one dict per program) — the one
    place the list-vs-dict compatibility lives; every reader
    (``analyze_cost``, ``parallel.aot``, ``parallel.auto_tune``, the
    attribution capture) routes through here instead of re-spelling the
    shim. Returns ``{}`` when the backend exposes nothing."""
    try:
        cost = compiled.cost_analysis() or {}
    except Exception:  # noqa: BLE001 - backend-dependent API
        logger.debug("cost_analysis unavailable", exc_info=True)
        return {}
    if isinstance(cost, (list, tuple)):  # old jax: one dict per program
        cost = cost[0] if cost else {}
    return dict(cost)


def compiled_peak_bytes(compiled) -> int:
    """Per-device HBM residency of a compiled program from
    ``memory_analysis()``: arguments (the sharded state + batch) plus
    transient temps plus outputs, minus donated (aliased) bytes so
    donation isn't double-counted — the same accounting the AOT
    fit-proof applies. 0 when the backend has no memory analysis."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent API
        logger.debug("memory_analysis unavailable", exc_info=True)
        return 0
    if mem is None:
        return 0
    return int(
        getattr(mem, "argument_size_in_bytes", 0)
        + getattr(mem, "temp_size_in_bytes", 0)
        + getattr(mem, "output_size_in_bytes", 0)
        - getattr(mem, "alias_size_in_bytes", 0)
    )


def derived_mfu(flops_per_step: float, step_time_s: float,
                peak_flops_per_s: float) -> float:
    """THE model-FLOPs-utilization formula: (FLOPs per step / step
    seconds) over hardware peak. ``ProfileResult.mfu``, the runtime
    attribution gauges (``telemetry.attribution``) and the bench all
    price MFU through this one function, so the one-shot profile and
    the live gauge can never drift apart. FLOPs and peak must share a
    basis (both per device, or both whole-mesh)."""
    if peak_flops_per_s <= 0 or step_time_s <= 0:
        return 0.0
    return flops_per_step / (step_time_s * peak_flops_per_s)


@dataclass
class CostReport:
    flops: float = 0.0
    bytes_accessed: float = 0.0
    peak_memory_bytes: int = 0
    # arithmetic intensity = flops / bytes: low values ⇒ HBM-bound on TPU.
    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0


def analyze_cost(fn: Callable, *args, **kwargs) -> CostReport:
    """Compile ``fn`` for the given args and read XLA's cost model."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    cost = cost_analysis_dict(compiled)
    report = CostReport(
        flops=float(cost.get("flops", 0.0)),
        bytes_accessed=float(cost.get("bytes accessed", 0.0)),
    )
    report.peak_memory_bytes = compiled_peak_bytes(compiled)
    return report


@dataclass
class ProfileResult:
    steps_per_sec: float
    step_time_ms: float
    flops_per_step: float
    achieved_flops_per_sec: float
    param_count: int
    peak_memory_bytes: int

    def mfu(self, peak_flops_per_sec: float) -> float:
        """Model FLOPs utilization against a hardware peak (the shared
        ``derived_mfu`` formula — same one the live attribution gauges
        use)."""
        return derived_mfu(self.flops_per_step,
                           1.0 / max(self.steps_per_sec, 1e-12),
                           peak_flops_per_sec)


class DryRunner:
    """Timed execution of a compiled train step (reference: dry_runner).

    Env knobs mirror the reference's
    ``ATORCH_DRYRUN_WARMUP_STEP``/``PROFILE_STEP``
    (``auto/accelerate.py:150-152``):
    ``DLROVER_TPU_DRYRUN_WARMUP`` / ``DLROVER_TPU_DRYRUN_STEPS``.
    """

    def __init__(self, warmup: Optional[int] = None, steps: Optional[int] = None):
        import os

        self.warmup = warmup if warmup is not None else int(
            os.environ.get("DLROVER_TPU_DRYRUN_WARMUP", "2")
        )
        self.steps = steps if steps is not None else int(
            os.environ.get("DLROVER_TPU_DRYRUN_STEPS", "5")
        )

    def profile(
        self,
        train_step: Callable,
        state: Any,
        batch: Any,
        rng: Optional[jax.Array] = None,
    ) -> ProfileResult:
        """Run warmup + timed steps; returns throughput + cost facts.

        ``train_step`` must be (state, batch, rng) -> (state, metrics) and
        already sharded/jitted (i.e. ``AccelerateResult.train_step``).
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        cost = analyze_cost(train_step, state, batch, rng)

        for _ in range(max(self.warmup, 1)):
            state, _ = train_step(state, batch, rng)
        jax.block_until_ready(state)

        t0 = time.perf_counter()
        for _ in range(max(self.steps, 1)):
            state, metrics = train_step(state, batch, rng)
        jax.block_until_ready(state)
        elapsed = time.perf_counter() - t0

        n = max(self.steps, 1)
        sps = n / elapsed
        result = ProfileResult(
            steps_per_sec=sps,
            step_time_ms=1000.0 * elapsed / n,
            flops_per_step=cost.flops,
            achieved_flops_per_sec=cost.flops * sps,
            param_count=count_params(state.params)
            if hasattr(state, "params") else count_params(state),
            peak_memory_bytes=cost.peak_memory_bytes,
        )
        logger.info(
            "dryrun: %.2f steps/s (%.1f ms/step), %.3g flops/step, "
            "%d params",
            result.steps_per_sec, result.step_time_ms,
            result.flops_per_step, result.param_count,
        )
        return result


class AProfiler:
    """Model-level profile summary (reference: AProfiler).

    Where the reference walks modules with hooks, here the unit of
    reporting is the pytree path: per-subtree parameter counts plus the
    whole-program XLA cost — per-op FLOPs formulas are obsolete under
    fusion, so they are intentionally not reproduced.
    """

    def __init__(self, params: Any):
        self._params = params

    def params_by_subtree(self, depth: int = 1) -> Dict[str, int]:
        out: Dict[str, int] = {}
        flat = jax.tree_util.tree_flatten_with_path(self._params)[0]
        for path, leaf in flat:
            key = "/".join(
                str(getattr(p, "key", getattr(p, "idx", p)))
                for p in path[:depth]
            )
            out[key] = out.get(key, 0) + leaf.size
        return out

    def summary(
        self, loss_fn: Optional[Callable] = None, batch: Any = None,
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, Any]:
        info: Dict[str, Any] = {
            "param_count": count_params(self._params),
            "param_bytes": param_bytes(self._params),
            "subtrees": self.params_by_subtree(),
        }
        if loss_fn is not None and batch is not None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            cost = analyze_cost(loss_fn, self._params, batch, rng)
            info["forward_flops"] = cost.flops
            info["bytes_accessed"] = cost.bytes_accessed
            info["arithmetic_intensity"] = cost.arithmetic_intensity
        return info
