"""Persistent XLA compilation cache — the TPU recovery accelerant.

Role parity: the reference's restore path (``docs/blogs/
stabilize_llm_training_cn.md:209-216``) wins its <2 min pod recovery by
restarting *processes*, not jobs; on TPU the equivalent dominant cost is
XLA recompilation after the restart (SURVEY §7: the <90 s restore budget
"forces aggressive compile caching"). Writing compiled executables to a
persistent on-disk cache makes the second compile of the same (program,
topology) a file read: a preempted-and-rescheduled worker skips straight
to restore + step.

Enabled automatically by ``trainer.bootstrap.init_worker`` and
``parallel.accelerate``; override the location with
``DLROVER_COMPILE_CACHE_DIR`` (empty string disables).
"""

from __future__ import annotations

import os
from typing import Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("utils.compile_cache")

ENV_CACHE_DIR = "DLROVER_COMPILE_CACHE_DIR"
_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "dlrover_tpu", "xla_cache"
)
_enabled_dir: Optional[str] = None
_fingerprint: Optional[str] = None


def machine_fingerprint() -> str:
    """Host/toolchain fingerprint the cache directory is keyed by.

    XLA:CPU AOT executables embed the *compile-time* host machine
    features; loading them on a host with different features logs
    "machine features don't match … could lead to SIGILL" — harmless
    noise at best, a crash hazard at worst. An image-baked or
    NFS-shared cache dir therefore must not be shared verbatim across
    hosts: every (arch, cpu flags, jaxlib version) combination gets its
    own subdirectory. Computed WITHOUT initializing a JAX backend — the
    cache is enabled before the (possibly slow, tunneled) backend comes
    up, and the executable cache key already separates backends.
    """
    global _fingerprint
    if _fingerprint is not None:
        return _fingerprint
    import hashlib
    import platform

    parts = [platform.machine(), platform.system()]
    try:
        import jaxlib

        parts.append(getattr(jaxlib, "__version__", ""))
    except Exception:  # noqa: BLE001 — fingerprint must never fail
        parts.append("")
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("flags"):
                    flags = line.split(":", 1)[1].split()
                    parts.append(" ".join(sorted(flags)))
                    break
    except OSError:
        pass
    _fingerprint = hashlib.sha256(
        "|".join(parts).encode()
    ).hexdigest()[:12]
    return _fingerprint


def cap_cpu_isa_for_cache() -> None:
    """Append ``--xla_cpu_max_isa=AVX2`` to ``XLA_FLAGS`` (idempotent).

    Default XLA:CPU tuning embeds AVX512-only pseudo-features
    (``+prefer-no-scatter``/``+prefer-no-gather``) that the AOT
    loader's host-feature detection never reports, so even SAME-host
    persistent-cache reloads log "machine features don't match …
    SIGILL" errors. The AVX2 cap makes cached CPU executables reload
    silently and portably. Callers decide cpu-ness (env hints differ
    per harness) and must call this before the CPU client initializes;
    afterwards it is a harmless no-op.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_max_isa" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_max_isa=AVX2"
        ).strip()


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit arg > ``DLROVER_COMPILE_CACHE_DIR`` env >
    ``~/.cache/dlrover_tpu/xla_cache``. An empty-string env value
    disables caching. The resolved directory gains a
    ``machine_fingerprint()`` subdirectory so one shared or image-baked
    root serves many hosts without cross-host AOT reuse. Idempotent;
    returns the active directory (or None when disabled).
    """
    global _enabled_dir
    if cache_dir is None:
        cache_dir = os.environ.get(ENV_CACHE_DIR, _DEFAULT_DIR)
    if not cache_dir:
        return None
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        # every cache user on a CPU-pinned process gets the ISA cap —
        # this is the chokepoint, so ad-hoc scripts (not just
        # conftest/bench/dryrun) produce and reload clean entries;
        # best-effort (no-op if the CPU client already initialized)
        cap_cpu_isa_for_cache()
    cache_dir = os.path.join(
        os.path.abspath(cache_dir), f"host-{machine_fingerprint()}"
    )
    if _enabled_dir == cache_dir:
        return _enabled_dir

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every executable: recovery time is dominated by the big
    # train-step compile, but warm-starting the small ones is free
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = cache_dir
    logger.info("persistent XLA compile cache at %s", cache_dir)
    return cache_dir


def cache_entries(cache_dir: Optional[str] = None) -> int:
    """Number of cached executables on disk for THIS host's
    fingerprinted subdirectory (0 if the dir is absent). ``cache_dir``
    is the un-fingerprinted root, as passed to
    ``enable_compile_cache``."""
    if cache_dir is not None:
        d = os.path.join(
            os.path.abspath(cache_dir), f"host-{machine_fingerprint()}"
        )
    elif _enabled_dir:
        d = _enabled_dir
    else:
        root = os.environ.get(ENV_CACHE_DIR, _DEFAULT_DIR)
        if not root:  # empty env value = caching disabled
            return 0
        d = os.path.join(
            os.path.abspath(root), f"host-{machine_fingerprint()}"
        )
    if not os.path.isdir(d):
        return 0
    return sum(
        1 for name in os.listdir(d)
        if os.path.isfile(os.path.join(d, name))
    )
