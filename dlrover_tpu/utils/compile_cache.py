"""Persistent XLA compilation cache — the TPU recovery accelerant.

Role parity: the reference's restore path (``docs/blogs/
stabilize_llm_training_cn.md:209-216``) wins its <2 min pod recovery by
restarting *processes*, not jobs; on TPU the equivalent dominant cost is
XLA recompilation after the restart (SURVEY §7: the <90 s restore budget
"forces aggressive compile caching"). Writing compiled executables to a
persistent on-disk cache makes the second compile of the same (program,
topology) a file read: a preempted-and-rescheduled worker skips straight
to restore + step — the warm half of the recovery decision tree in
``docs/operations.md`` (the live half never leaves the process at all,
``ElasticTrainer.live_reshard``).

Enabled automatically by ``trainer.bootstrap.init_worker`` and
``parallel.accelerate``; override the location with
``DLROVER_COMPILE_CACHE_DIR`` (empty string disables). Cache traffic is
observable: hit/miss counters ride the telemetry registry
(``jax.monitoring`` listener) and ``tpurun cache`` prints the live
stats.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("utils.compile_cache")

ENV_CACHE_DIR = "DLROVER_COMPILE_CACHE_DIR"
# the one place the CPU ISA cap is spelled (cap_cpu_isa_for_cache and
# every harness that builds a child-process XLA_FLAGS from scratch)
CPU_ISA_CAP_FLAG = "--xla_cpu_max_isa=AVX2"
_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "dlrover_tpu", "xla_cache"
)
_enabled_dir: Optional[str] = None
# fingerprint memo, keyed by the topology hint it was computed under (a
# worker that re-rendezvouses at a new world size must not reuse the old
# topology's fingerprint)
_fingerprints: Dict[str, str] = {}
_monitor_registered = False
# process-local cache traffic, mirrored into the telemetry registry by
# the monitoring listener; kept here too so cache_stats() works even
# with telemetry off
_traffic = {"hits": 0, "misses": 0, "requests": 0}


def topology_hint() -> str:
    """Deterministic description of the topology this process compiles
    for, WITHOUT initializing a JAX backend (the cache is enabled before
    the — possibly slow, tunneled — backend comes up).

    Derived from the launch environment: the platform pin, the virtual
    host-device count, and the distributed process count the agent
    injects. Two processes whose hints differ can never share AOT
    artifacts; a jax upgrade changes the fingerprint through the
    version component, so stale executables are structurally
    unreachable rather than relied on to key-miss.
    """
    parts = [os.environ.get("JAX_PLATFORMS", "")]
    flags = os.environ.get("XLA_FLAGS", "")
    for token in flags.split():
        if "xla_force_host_platform_device_count" in token:
            parts.append(token.split("=", 1)[-1])
    # the jax.distributed coordinates the agent hands its workers
    for env in ("DLROVER_NUM_PROCESSES", "TPU_WORKER_HOSTNAMES"):
        val = os.environ.get(env, "")
        if val:
            parts.append(f"{env}={val}")
    return "|".join(p for p in parts if p)


def machine_fingerprint() -> str:
    """Host/toolchain/topology fingerprint the cache directory is keyed
    by.

    XLA:CPU AOT executables embed the *compile-time* host machine
    features; loading them on a host with different features logs
    "machine features don't match … could lead to SIGILL" — harmless
    noise at best, a crash hazard at worst. An image-baked or
    NFS-shared cache dir therefore must not be shared verbatim across
    hosts: every (arch, cpu flags, jax/jaxlib version, topology hint)
    combination gets its own subdirectory. The jax *and* jaxlib
    versions are both included so an upgrade of either can never serve
    a stale artifact, and the topology hint keys same-host processes
    compiled for different worlds apart. Computed WITHOUT initializing
    a JAX backend — the cache is enabled before the (possibly slow,
    tunneled) backend comes up.
    """
    hint = topology_hint()
    cached = _fingerprints.get(hint)
    if cached is not None:
        return cached
    import hashlib
    import platform

    parts = [platform.machine(), platform.system(), hint]
    try:
        import jax
        import jaxlib

        parts.append(getattr(jax, "__version__", ""))
        parts.append(getattr(jaxlib, "__version__", ""))
    except Exception as e:  # noqa: BLE001 — fingerprint must never fail
        logger.warning("jax version unavailable for cache fingerprint "
                       "(%s: %s)", type(e).__name__, e)
        parts.append("")
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("flags"):
                    flags = line.split(":", 1)[1].split()
                    parts.append(" ".join(sorted(flags)))
                    break
    except OSError:
        pass
    fp = hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]
    _fingerprints[hint] = fp
    return fp


def cap_cpu_isa_for_cache() -> None:
    """Append ``--xla_cpu_max_isa=AVX2`` to ``XLA_FLAGS`` (idempotent).

    Default XLA:CPU tuning embeds AVX512-only pseudo-features
    (``+prefer-no-scatter``/``+prefer-no-gather``) that the AOT
    loader's host-feature detection never reports, so even SAME-host
    persistent-cache reloads log "machine features don't match …
    SIGILL" errors. The AVX2 cap makes cached CPU executables reload
    silently and portably. Callers decide cpu-ness (env hints differ
    per harness) and must call this before the CPU client initializes;
    afterwards it is a harmless no-op.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_max_isa" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + CPU_ISA_CAP_FLAG).strip()


def _register_cache_monitor() -> None:
    """Mirror jax's compilation-cache monitoring events into the
    telemetry registry (and the process-local traffic counters), once.

    A warm restart that truly skipped recompilation shows hits > 0 and
    misses == 0 here — the machine-checkable form of the "zero
    recompiles on a same-topology resume" recovery claim.
    """
    global _monitor_registered
    if _monitor_registered:
        return
    try:
        from jax import monitoring
    except Exception as e:  # noqa: BLE001 — observability must not gate
        logger.warning("jax.monitoring unavailable; compile-cache "
                       "traffic not exported (%s: %s)",
                       type(e).__name__, e)
        return
    from dlrover_tpu.telemetry import get_registry, names as tm

    def _on_event(event: str, **_kw) -> None:
        reg = get_registry()
        if event == "/jax/compilation_cache/cache_hits":
            _traffic["hits"] += 1
            reg.counter(tm.COMPILE_CACHE_HITS,
                        help="persistent-cache compiles served from "
                             "disk").inc()
        elif event == "/jax/compilation_cache/cache_misses":
            _traffic["misses"] += 1
            reg.counter(tm.COMPILE_CACHE_MISSES,
                        help="compiles that went to XLA and were "
                             "written back").inc()
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            _traffic["requests"] += 1

    monitoring.register_event_listener(_on_event)
    _monitor_registered = True


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit arg > ``DLROVER_COMPILE_CACHE_DIR`` env >
    ``~/.cache/dlrover_tpu/xla_cache``. An empty-string env value
    disables caching. The resolved directory gains a
    ``machine_fingerprint()`` subdirectory so one shared or image-baked
    root serves many hosts without cross-host AOT reuse. Idempotent;
    returns the active directory (or None when disabled).
    """
    global _enabled_dir
    if cache_dir is None:
        cache_dir = os.environ.get(ENV_CACHE_DIR, _DEFAULT_DIR)
    if not cache_dir:
        return None
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        # every cache user on a CPU-pinned process gets the ISA cap —
        # this is the chokepoint, so ad-hoc scripts (not just
        # conftest/bench/dryrun) produce and reload clean entries;
        # best-effort (no-op if the CPU client already initialized)
        cap_cpu_isa_for_cache()
    cache_dir = os.path.join(
        os.path.abspath(cache_dir), f"host-{machine_fingerprint()}"
    )
    _register_cache_monitor()
    if _enabled_dir == cache_dir:
        return _enabled_dir

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every executable: recovery time is dominated by the big
    # train-step compile, but warm-starting the small ones is free
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = cache_dir
    logger.info("persistent XLA compile cache at %s", cache_dir)
    return cache_dir


def _resolve_host_dir(cache_dir: Optional[str]) -> Optional[str]:
    """The fingerprinted per-host directory for ``cache_dir`` (the
    un-fingerprinted root), the active dir, or the env/default root."""
    if cache_dir is not None:
        return os.path.join(
            os.path.abspath(cache_dir), f"host-{machine_fingerprint()}"
        )
    if _enabled_dir:
        return _enabled_dir
    root = os.environ.get(ENV_CACHE_DIR, _DEFAULT_DIR)
    if not root:  # empty env value = caching disabled
        return None
    return os.path.join(
        os.path.abspath(root), f"host-{machine_fingerprint()}"
    )


def cache_entries(cache_dir: Optional[str] = None) -> int:
    """Number of cached executables on disk for THIS host's
    fingerprinted subdirectory (0 if the dir is absent). ``cache_dir``
    is the un-fingerprinted root, as passed to
    ``enable_compile_cache``."""
    d = _resolve_host_dir(cache_dir)
    if not d or not os.path.isdir(d):
        return 0
    return sum(
        1 for name in os.listdir(d)
        if os.path.isfile(os.path.join(d, name))
    )


def cache_stats(cache_dir: Optional[str] = None) -> Dict:
    """One snapshot for operators (``tpurun cache``): where the cache
    lives, how many executables it holds, and this process's traffic.
    Also refreshes the entry-count gauge in the telemetry registry."""
    from dlrover_tpu.telemetry import get_registry, names as tm

    entries = cache_entries(cache_dir)
    get_registry().gauge(
        tm.COMPILE_CACHE_ENTRIES,
        help="executables in this host's persistent compile cache",
    ).set(entries)
    return {
        "dir": _resolve_host_dir(cache_dir),
        # configured: a cache root resolves (explicit, env, or default)
        # — an empty DLROVER_COMPILE_CACHE_DIR is the only way off.
        # active: enable_compile_cache() ran in THIS process — the
        # difference matters when debugging "why did the warm restart
        # recompile": configured-but-not-active means nothing ever
        # pointed jax at the cache here.
        "configured": _resolve_host_dir(cache_dir) is not None,
        "active": _enabled_dir is not None,
        "entries": entries,
        "fingerprint": machine_fingerprint(),
        "topology_hint": topology_hint(),
        "hits": _traffic["hits"],
        "misses": _traffic["misses"],
        "requests": _traffic["requests"],
    }
