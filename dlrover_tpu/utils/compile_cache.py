"""Persistent XLA compilation cache — the TPU recovery accelerant.

Role parity: the reference's restore path (``docs/blogs/
stabilize_llm_training_cn.md:209-216``) wins its <2 min pod recovery by
restarting *processes*, not jobs; on TPU the equivalent dominant cost is
XLA recompilation after the restart (SURVEY §7: the <90 s restore budget
"forces aggressive compile caching"). Writing compiled executables to a
persistent on-disk cache makes the second compile of the same (program,
topology) a file read: a preempted-and-rescheduled worker skips straight
to restore + step.

Enabled automatically by ``trainer.bootstrap.init_worker`` and
``parallel.accelerate``; override the location with
``DLROVER_COMPILE_CACHE_DIR`` (empty string disables).
"""

from __future__ import annotations

import os
from typing import Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("utils.compile_cache")

ENV_CACHE_DIR = "DLROVER_COMPILE_CACHE_DIR"
_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "dlrover_tpu", "xla_cache"
)
_enabled_dir: Optional[str] = None


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit arg > ``DLROVER_COMPILE_CACHE_DIR`` env >
    ``~/.cache/dlrover_tpu/xla_cache``. An empty-string env value
    disables caching. Idempotent; returns the active directory (or None
    when disabled).
    """
    global _enabled_dir
    if cache_dir is None:
        cache_dir = os.environ.get(ENV_CACHE_DIR, _DEFAULT_DIR)
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(cache_dir)
    if _enabled_dir == cache_dir:
        return _enabled_dir

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every executable: recovery time is dominated by the big
    # train-step compile, but warm-starting the small ones is free
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = cache_dir
    logger.info("persistent XLA compile cache at %s", cache_dir)
    return cache_dir


def cache_entries(cache_dir: Optional[str] = None) -> int:
    """Number of cached executables on disk (0 if the dir is absent)."""
    d = cache_dir or _enabled_dir or os.environ.get(
        ENV_CACHE_DIR, _DEFAULT_DIR
    )
    if not d or not os.path.isdir(d):
        return 0
    return sum(
        1 for name in os.listdir(d)
        if os.path.isfile(os.path.join(d, name))
    )
