"""Pallas TPU flash attention.

Role parity: the FlashAttention adapters the reference injects into HF
models (``atorch/atorch/modules/transformer/layers.py:729-1502`` — thin
wrappers over the external CUDA ``flash_attn`` package). Here the kernel
itself is in-tree, written for the TPU memory hierarchy: Q/K/V blocks are
streamed HBM->VMEM by the pallas pipeline, the [Bq, Bk] logits tile lives
only in registers/VMEM, and softmax is computed online (running max +
normalizer in VMEM scratch carried across the K grid dimension), so HBM
traffic is O(S*D) instead of O(S^2).

Forward is a Pallas kernel; backward recomputes attention blockwise via the
same online-softmax scheme expressed in XLA ops (no O(S^2) residuals are
saved — ``jax.checkpoint``-friendly). Long-context scaling across chips is
handled one level up by ``ops.ring_attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dlrover_tpu.ops.attention_ref import mha_reference

NEG_INF = float(jnp.finfo(jnp.float32).min)
LANES = 128


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref,  # [1, 1, Bq|Bk, D] VMEM blocks
    o_ref, lse_ref,  # [1, 1, Bq, D], [1, 1, Bq]
    m_scratch, l_scratch, acc_scratch,  # VMEM carries across the k grid dim
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    i = pl.program_id(2)  # q block index
    j = pl.program_id(3)  # k block index (innermost, sequential on TPU)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # with causal masking, blocks fully above the diagonal contribute nothing
    block_needed = jnp.logical_or(
        jnp.logical_not(causal), j * block_k <= i * block_q + block_q - 1
    )

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [Bq, Bk]

        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + i * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            ) + j * block_k
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_scratch[:, :1]  # [Bq, 1]
        l_prev = l_scratch[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [Bq, Bk]
        alpha = jnp.exp(m_prev - m_new)  # correction for old accumulator
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        m = m_scratch[:, :1]
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        # logsumexp residual for the blockwise backward pass
        lse = m + jnp.log(l_safe)
        lse_ref[0, 0, :] = jnp.broadcast_to(lse[:, 0], lse_ref.shape[2:])


def _flash_forward(
    q, k, v, *, scale: float, causal: bool,
    block_q: int, block_k: int, interpret: bool,
):
    batch, heads, s_q, head_dim = q.shape
    s_k = k.shape[2]
    if causal and s_q != s_k:
        raise ValueError(
            f"causal flash attention requires s_q == s_k (got {s_q} vs "
            f"{s_k}); use causal=False for cross attention"
        )
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k:
        raise ValueError(
            f"sequence lengths ({s_q}, {s_k}) must be divisible by blocks "
            f"({block_q}, {block_k})"
        )
    grid = (batch, heads, s_q // block_q, s_k // block_k)

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, h, i, j: (b, h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, s_q), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, LANES)),  # running max m
            _vmem((block_q, LANES)),  # running normalizer l
            _vmem((block_q, head_dim)),  # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Memory-efficient attention; differentiable (blockwise recompute
    backward from the saved logsumexp, no quadratic residuals)."""
    out, _ = _flash_attention_fwd(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return out


def _resolve(scale, head_dim, interpret):
    scale = scale if scale is not None else 1.0 / (head_dim ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _flash_attention_fwd(q, k, v, causal, scale, block_q, block_k,
                         interpret):
    scale_v, interp = _resolve(scale, q.shape[-1], interpret)
    out, lse = _flash_forward(
        q, k, v, scale=scale_v, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interp,
    )
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(causal, scale, block_q, block_k, interpret,
                         residuals, g):
    """Blockwise backward from the saved logsumexp.

    A scan over K blocks recomputes each [S, Bk] probability tile from
    (q, k_block, lse) — peak extra memory is O(S * Bk), never O(S^2):

      p    = exp(q k_b^T * scale - lse)
      dv_b = p^T g
      ds   = p * (g v_b^T - delta) * scale,  delta = rowsum(g * o)
      dq  += ds k_b ;  dk_b = ds^T q
    """
    q, k, v, out, lse = residuals
    scale_v, _ = _resolve(scale, q.shape[-1], interpret)

    f32 = jnp.float32
    qf, kf, vf, gf, of = (x.astype(f32) for x in (q, k, v, g, out))
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bk = min(block_k, s_k)
    nk = s_k // bk

    delta = jnp.sum(gf * of, axis=-1, keepdims=True)  # [B,H,Sq,1]
    lse_e = lse[..., None]  # [B,H,Sq,1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (s_q, bk), 0)

    k_blocks = kf.reshape(b, h, nk, bk, d).transpose(2, 0, 1, 3, 4)
    v_blocks = vf.reshape(b, h, nk, bk, d).transpose(2, 0, 1, 3, 4)

    def kblock_step(dq_acc, inputs):
        j, k_b, v_b = inputs  # [B,H,Bk,D]
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_b, preferred_element_type=f32
        ) * scale_v  # [B,H,Sq,Bk]
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (s_q, bk), 1) + j * bk
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse_e)  # [B,H,Sq,Bk]; exact probs via saved lse
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v_b)
        ds = p * (dp - delta) * scale_v
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, k_b)
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk_b, dv_b)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kblock_step, dq0,
        (jnp.arange(nk), k_blocks, v_blocks),
    )
    dk = dk_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, s_k, d)
    dv = dv_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, s_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def attention(q, k, v, causal=True, scale=None, use_flash=True, **kwargs):
    """Dispatch: Pallas flash kernel on TPU; XLA reference elsewhere (the
    interpreter-mode kernel is orders of magnitude slower than XLA on
    CPU/GPU, so it is only used when explicitly requested via kwargs)."""
    on_tpu = jax.default_backend() == "tpu"
    if use_flash and (on_tpu or kwargs.get("interpret")):
        return flash_attention(q, k, v, causal, scale, **kwargs)
    return mha_reference(q, k, v, causal=causal, scale=scale)
