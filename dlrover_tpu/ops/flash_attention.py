"""Pallas TPU flash attention.

Role parity: the FlashAttention adapters the reference injects into HF
models (``atorch/atorch/modules/transformer/layers.py:729-1502`` — thin
wrappers over the external CUDA ``flash_attn`` package). Here the kernel
itself is in-tree, written for the TPU memory hierarchy: Q/K/V blocks are
streamed HBM->VMEM by the pallas pipeline, the [Bq, Bk] logits tile lives
only in registers/VMEM, and softmax is computed online (running max +
normalizer in VMEM scratch carried across the K grid dimension), so HBM
traffic is O(S*D) instead of O(S^2).

Forward and backward are Pallas kernels (FlashAttention-2 style: a dKV
pass with k blocks outer / q blocks inner, and a dQ pass with q outer / k
inner), recomputing probability tiles from the saved logsumexp — no
O(S^2) residuals are ever materialized. All MXU dots run on the storage
dtype (bf16) with f32 accumulation. Long-context scaling across chips is
handled one level up by ``ops.ring_attention``.

GQA is native: K/V may carry fewer heads than Q (``num_kv_heads``
divides ``num_heads``); the kernels index the shared KV block per query
group (``h // group`` in the BlockSpec index maps) instead of
materializing repeated heads, so HBM traffic for K/V is ``kv/h`` of the
MHA equivalent (the reference pays the full repeat before its CUDA
kernel, ``modules/transformer/layers.py:1268``). ``flash_attention_lse``
additionally returns the per-row logsumexp and is differentiable in it,
which is what lets ``ring_attention`` rescale and merge per-ring-step
outputs without ever forming an [S, S] tile.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dlrover_tpu.ops.attention_ref import mha_reference

NEG_INF = float(jnp.finfo(jnp.float32).min)
LANES = 128


def _fit_block(requested: int, dim: int) -> int:
    """Largest divisor of ``dim`` that is <= ``requested`` — block sizes
    must tile the sequence exactly, but callers shouldn't have to match
    the defaults to their sequence length. Sequences whose only fitting
    blocks would break the TPU sublane rule (multiple of 8, unless the
    block covers the whole dim) are rejected with a clear error rather
    than silently degrading to tiny blocks."""
    b = min(requested, dim)
    while dim % b:
        b -= 1
    if b != dim and b % 8:
        raise ValueError(
            f"no legal block tiling for sequence length {dim} under block "
            f"size {requested}: best divisor {b} is not a multiple of 8; "
            "pad the sequence to a multiple of 8"
        )
    return b


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref,  # [1, 1, Bq|Bk, D] VMEM blocks
    *rest,  # (+seg_q_ref, seg_k_ref when segmented; +prefix_ref when
    # prefix) o_ref, lse_ref, scratch
    scale: float, causal: bool, block_q: int, block_k: int,
    segmented: bool = False, prefix: bool = False,
):
    if segmented:
        (seg_q_ref, seg_k_ref, o_ref, lse_ref,
         m_scratch, l_scratch, acc_scratch) = rest
    elif prefix:
        seg_q_ref = seg_k_ref = None
        (prefix_ref, o_ref, lse_ref,
         m_scratch, l_scratch, acc_scratch) = rest
    else:
        seg_q_ref = seg_k_ref = None
        o_ref, lse_ref, m_scratch, l_scratch, acc_scratch = rest
    i = pl.program_id(2)  # q block index
    j = pl.program_id(3)  # k block index (innermost, sequential on TPU)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # with causal masking, blocks fully above the diagonal contribute
    # nothing; in prefix-LM mode a block is also needed when it holds
    # prefix columns (bidirectionally visible)
    causal_needed = jnp.logical_or(
        jnp.logical_not(causal), j * block_k <= i * block_q + block_q - 1
    )
    if prefix:
        p_len = prefix_ref[0, 0, 0]
        block_needed = jnp.logical_or(causal_needed, j * block_k < p_len)
    else:
        block_needed = causal_needed

    @pl.when(block_needed)
    def _compute():
        # inputs stay in their storage dtype (bf16) so the MXU runs at
        # full rate; only the accumulators are f32
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [Bq, Bk] f32

        if causal or prefix:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + i * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            ) + j * block_k
            allowed = rows >= cols
            if prefix:
                # prefix-LM: the prompt is bidirectionally visible
                allowed = jnp.logical_or(allowed, cols < p_len)
            s = jnp.where(allowed, s, NEG_INF)
        if segmented:
            # packed sequences: tokens attend only within their segment
            sq = seg_q_ref[0, 0, 0, :]  # [Bq] int32
            sk = seg_k_ref[0, 0, 0, :]  # [Bk]
            s = jnp.where(sq[:, None] == sk[None, :], s, NEG_INF)

        m_prev = m_scratch[:, :1]  # [Bq, 1]
        l_prev = l_scratch[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        if segmented or prefix:
            # a visited block can be FULLY masked for some rows (their
            # segment's keys live elsewhere; or a prefix-needed block
            # past both the diagonal and the prefix for early rows):
            # m_new stays NEG_INF there and exp(NEG_INF - NEG_INF)
            # would poison the accumulator with NaN. Clamp the
            # subtrahend — those rows have l_prev == 0, so any finite
            # alpha is harmless.
            m_sub = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
        else:
            m_sub = m_new
        p = jnp.exp(s - m_sub)  # [Bq, Bk]
        alpha = jnp.exp(m_prev - m_sub)  # correction for old accumulator
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        m = m_scratch[:, :1]
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        # logsumexp residual for the blockwise backward pass
        lse = m + jnp.log(l_safe)
        lse_ref[0, 0, 0, :] = lse[:, 0]


def _check_mosaic_lane_block(interpret: bool, block: int, dim: int,
                             what: str) -> None:
    """The lse/delta/segment-id operands ride the LANE dimension in
    (1, 1, 1, block)-shaped VMEM blocks, and Mosaic requires a block's
    last dim to be a multiple of 128 or cover the whole array dim.
    Production tiles (512/1024) always satisfy this; a small block on
    the real-TPU path must fail HERE with an actionable message, not in
    the lowering (interpret mode never enforces tiling — the round-4
    deviceless lowering drive is what surfaced it)."""
    if not interpret and block != dim and block % LANES:
        raise ValueError(
            f"TPU Mosaic lowering needs {what}={block} to be a "
            f"multiple of {LANES} or to cover the whole sequence "
            f"({dim}): the per-row residuals are lane-blocked by "
            f"{what}. Use {what}>=128 (or interpret=True off-TPU)."
        )


def _group_size(q, k) -> int:
    """Query heads per KV head (1 = MHA). Static, from the shapes."""
    heads, kv_heads = q.shape[1], k.shape[1]
    if heads % kv_heads:
        raise ValueError(
            f"num_heads {heads} not divisible by num_kv_heads {kv_heads}"
        )
    return heads // kv_heads


def _flash_forward(
    q, k, v, *, scale: float, causal: bool,
    block_q: int, block_k: int, interpret: bool,
    segment_ids=None,  # [B, S_q] int32 — packed-sequence masking
    segment_ids_kv=None,  # [B, S_k] — kv-side ids when they differ
    # (ring steps: local q vs a VISITING kv shard); defaults to the
    # q-side array
    prefix_len=None,  # [B] int32 — prefix-LM (bidirectional prompt)
):
    batch, heads, s_q, head_dim = q.shape
    s_k = k.shape[2]
    group = _group_size(q, k)
    if causal and s_q != s_k:
        raise ValueError(
            f"causal flash attention requires s_q == s_k (got {s_q} vs "
            f"{s_k}); use causal=False for cross attention"
        )
    block_q = _fit_block(block_q, s_q)
    block_k = _fit_block(block_k, s_k)
    _check_mosaic_lane_block(interpret, block_q, s_q, "block_q")
    if segment_ids is not None:
        _check_mosaic_lane_block(interpret, block_k, s_k, "block_k")
    grid = (batch, heads, s_q // block_q, s_k // block_k)
    segmented = segment_ids is not None
    prefixed = prefix_len is not None
    if segmented and prefixed:
        raise ValueError("segment_ids and prefix_len are mutually "
                         "exclusive masking modes")

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, segmented=segmented,
        prefix=prefixed,
    )
    in_specs = [
        pl.BlockSpec((1, 1, block_q, head_dim),
                     lambda b, h, i, j: (b, h, i, 0)),
        # GQA: query head h reads KV head h // group
        pl.BlockSpec((1, 1, block_k, head_dim),
                     lambda b, h, i, j: (b, h // group, j, 0)),
        pl.BlockSpec((1, 1, block_k, head_dim),
                     lambda b, h, i, j: (b, h // group, j, 0)),
    ]
    operands = [q, k, v]
    if segmented:
        seg4q = segment_ids.astype(jnp.int32).reshape(batch, 1, 1, s_q)
        seg_kv = (segment_ids_kv if segment_ids_kv is not None
                  else segment_ids)
        seg4k = seg_kv.astype(jnp.int32).reshape(batch, 1, 1, s_k)
        # broadcast over heads: index map pins the head/row dims to 0
        in_specs.append(pl.BlockSpec((1, 1, 1, block_q),
                                     lambda b, h, i, j: (b, 0, 0, i)))
        in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                     lambda b, h, i, j: (b, 0, 0, j)))
        operands += [seg4q, seg4k]
    if prefixed:
        # [B, 1, LANES] so the BLOCK's last two dims (1, LANES)
        # equal the array's — Mosaic requires the trailing two block
        # dims be (8,128)-divisible OR exactly the array dims, and a
        # (1, LANES) block over a [B, LANES] array violates that for
        # B > 1 (caught by deviceless lowering; interpret mode never
        # enforces tiling). The kernel reads lane 0.
        p2 = jnp.broadcast_to(
            prefix_len.astype(jnp.int32)[:, None, None],
            (batch, 1, LANES))
        in_specs.append(pl.BlockSpec((1, 1, LANES),
                                     lambda b, h, i, j: (b, 0, 0)))
        operands.append(p2)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            # [B, H, 1, Sq] so the last-two block dims (1, block_q) satisfy
            # the TPU (8, 128) tiling rule; squeezed after the call
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, 1, s_q), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, LANES)),  # running max m
            _vmem((block_q, LANES)),  # running normalizer l
            _vmem((block_q, head_dim)),  # output accumulator
        ],
        interpret=interpret,
    )(*operands)


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9)
)
def flash_attention_lse(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,  # [B, H_kv, S, D] (H_kv divides H)
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
):
    """Attention returning ``(out, lse)`` where ``lse[b,h,s]`` is the
    row logsumexp of the (scaled, masked) scores. Differentiable in both
    outputs — the lse cotangent folds into the backward's delta term
    (``ds = p * (dp - (delta - dlse))``), which is what makes the
    ring-attention merge exact under autodiff.

    ``block_q_bwd``/``block_k_bwd`` (0 = same as forward) tile the
    backward kernels independently: the dKV/dQ passes hold more live
    VMEM tiles than the forward, so their optimum is usually smaller —
    a long-context tuning lever (``BENCH_BLOCK_Q_BWD``)."""
    (out, lse), _ = _flash_attention_lse_fwd(
        q, k, v, causal, scale, block_q, block_k, interpret,
        block_q_bwd, block_k_bwd,
    )
    return out, lse


def flash_attention(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
) -> jax.Array:
    """Memory-efficient attention; differentiable (blockwise recompute
    backward from the saved logsumexp, no quadratic residuals)."""
    return flash_attention_lse(
        q, k, v, causal, scale, block_q, block_k, interpret,
        block_q_bwd, block_k_bwd,
    )[0]


def ambient_shard_mesh():
    """The ambient mesh when tracing under a mesh context (``set_mesh``
    or the legacy ``with mesh:`` thread-resources form — see
    ``shard_compat.ambient_mesh_with_axes``) with >1 device on the
    flash-relevant (data/fsdp/tensor) axes; None when single-device,
    unsharded, or under a partial mesh missing one of those axes (the
    sharded wrapper's PartitionSpec names all three)."""
    from dlrover_tpu.ops.shard_compat import ambient_mesh_with_axes

    return ambient_mesh_with_axes(("data", "fsdp", "tensor"))


def flash_attention_auto(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
) -> jax.Array:
    """``flash_attention`` that routes itself through the ``shard_map``
    wrapper whenever the ambient mesh is non-trivial — GSPMD cannot
    auto-partition a Mosaic custom call, so every model's flash call
    site must make this choice; centralizing it here keeps them all
    multi-chip-safe."""
    mesh = ambient_shard_mesh()
    if mesh is not None:
        return flash_attention_sharded(
            q, k, v, mesh, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
            block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
        )
    return flash_attention(q, k, v, causal, scale, block_q, block_k,
                           interpret, block_q_bwd, block_k_bwd)


def _shard_mapped_attention(mesh, body, q, k, v, extras=(),
                            extra_ndims=(), batch_axes=("data", "fsdp"),
                            head_axis: Optional[str] = "tensor"):
    """Shared shard_map routing for every flash variant: GQA head-shard
    legalization, (batch, head) partition specs, and the shard_map
    keyword-compat shim live HERE once. ``extras`` are additional
    operands sharded along batch only (segment ids, prefix lengths);
    ``extra_ndims`` gives each one's rank so its spec pads with None."""
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.ops.shard_compat import (
        get_shard_map,
        shard_map_check_kwargs,
    )

    shard_map = get_shard_map()

    if head_axis is not None:
        sizes = dict(zip(mesh.axis_names, mesh.axis_sizes))
        ways = sizes.get(head_axis, 1)
        rep = minimal_kv_repeat(k.shape[1], q.shape[1], ways)
        if rep > 1:
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
    spec = P(batch_axes, head_axis, None, None)
    extra_specs = tuple(
        P(batch_axes, *([None] * (nd - 1))) for nd in extra_ndims
    )
    check_kw = shard_map_check_kwargs(shard_map)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec) + extra_specs, out_specs=spec,
        **check_kw,
    )(q, k, v, *extras)


def flash_attention_segmented_auto(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array,  # [B, S]
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
) -> jax.Array:
    """Multi-chip-safe ``flash_attention_segmented``: same shard_map
    routing discipline as ``flash_attention_auto`` — GSPMD cannot
    partition the Mosaic call, and segmented attention with an unsharded
    sequence is embarrassingly parallel over (batch, head) shards, with
    segment ids sharded along batch only."""
    mesh = ambient_shard_mesh()
    if mesh is None:
        return flash_attention_segmented(
            q, k, v, segment_ids, causal, scale, block_q, block_k,
            interpret, block_q_bwd, block_k_bwd,
        )

    def body(ql, kl, vl, segl):
        return flash_attention_segmented(
            ql, kl, vl, segl, causal, scale, block_q, block_k,
            interpret, block_q_bwd, block_k_bwd,
        )

    return _shard_mapped_attention(
        mesh, body, q, k, v, extras=(segment_ids,), extra_ndims=(2,),
        batch_axes=batch_axes, head_axis=head_axis,
    )


def minimal_kv_repeat(kv_heads: int, num_heads: int, ways: int) -> int:
    """Smallest repeat making ``kv_heads * rep`` divisible by ``ways``
    while still dividing ``num_heads`` (the GQA head-shard legalizer
    shared by the sharded flash wrapper and ring attention; the planner
    prices the same factor, ``planner.ring_kv_repeat``)."""
    if kv_heads <= 0 or ways <= 1 or kv_heads % ways == 0:
        return 1
    for rep in range(1, num_heads // kv_heads + 1):
        if (kv_heads * rep) % ways == 0 and num_heads % (
            kv_heads * rep
        ) == 0:
            return rep
    raise ValueError(
        f"cannot shard {kv_heads} kv heads (of {num_heads} query heads) "
        f"over {ways} ways"
    )


def flash_attention_sharded(
    q: jax.Array,  # global [B, H, S, D]
    k: jax.Array,  # global [B, H_kv, S, D]
    v: jax.Array,
    mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
) -> jax.Array:
    """The multi-chip flash path: GSPMD cannot auto-partition a Mosaic
    custom call, so the kernel runs under ``shard_map`` with batch on
    the data axes and heads on the tensor axis — attention with an
    unsharded sequence is embarrassingly parallel over (batch, head)
    shards, so the body needs zero collectives. The (seq-sharded)
    counterpart is ``ops.ring_attention``."""

    def body(ql, kl, vl):
        return flash_attention(ql, kl, vl, causal, scale,
                               block_q, block_k, interpret,
                               block_q_bwd, block_k_bwd)

    return _shard_mapped_attention(
        mesh, body, q, k, v, batch_axes=batch_axes, head_axis=head_axis,
    )


def _resolve(scale, head_dim, interpret):
    scale = scale if scale is not None else 1.0 / (head_dim ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _flash_attention_lse_fwd(q, k, v, causal, scale, block_q, block_k,
                             interpret, block_q_bwd=0, block_k_bwd=0):
    scale_v, interp = _resolve(scale, q.shape[-1], interpret)
    out, lse = _flash_forward(
        q, k, v, scale=scale_v, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interp,
    )
    lse = lse.reshape(q.shape[0], q.shape[1], q.shape[2])
    return (out, lse), (q, k, v, out, lse)


def _recompute_p(q, k, lse, *, scale, causal, i, j, block_q, block_k,
                 seg_q=None, seg_k=None, prefix_len=None):
    """Recompute the [Bq, Bk] probability tile from (q, k, lse): exact
    probs p = exp(q k^T * scale - lse) with causal (segment / prefix)
    masking re-applied."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [Bq, Bk] f32
    if causal or prefix_len is not None:
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        ) + i * block_q
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        ) + j * block_k
        allowed = rows >= cols
        if prefix_len is not None:
            allowed = jnp.logical_or(allowed, cols < prefix_len)
        s = jnp.where(allowed, s, NEG_INF)
    if seg_q is not None:
        s = jnp.where(seg_q[:, None] == seg_k[None, :], s, NEG_INF)
        # rows whose segment has no keys in this block: s == NEG_INF and
        # (for all-pad rows) lse == NEG_INF too — clamp so the masked
        # entries stay exactly 0 instead of exp(NEG_INF - NEG_INF) = NaN
        lse = jnp.where(lse <= NEG_INF * 0.5, 0.0, lse)
    return jnp.exp(s - lse[:, None])


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,  # VMEM blocks
    *rest,  # (+seg refs / prefix_ref per mode) dk_ref, dv_ref, scratch
    scale: float, causal: bool, block_q: int, block_k: int,
    segmented: bool = False, prefix: bool = False,
):
    prefix_ref = seg_q_ref = seg_k_ref = None
    if segmented:
        (seg_q_ref, seg_k_ref, dk_ref, dv_ref,
         dk_scratch, dv_scratch) = rest
    elif prefix:
        prefix_ref, dk_ref, dv_ref, dk_scratch, dv_scratch = rest
    else:
        dk_ref, dv_ref, dk_scratch, dv_scratch = rest
    # grid (batch, kv_head, j, g, i): the two innermost (sequential)
    # dims sweep the query heads of this KV head's group and the q
    # blocks, so dk/dv accumulate over both without write conflicts.
    j = pl.program_id(2)  # k block index
    g = pl.program_id(3)  # query-head index within the KV group
    i = pl.program_id(4)  # q block index (innermost, sequential)
    ng = pl.num_programs(3)
    nq = pl.num_programs(4)

    @pl.when(jnp.logical_and(g == 0, i == 0))
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    # with causal masking, q blocks strictly above the k block's diagonal
    # see none of these keys; prefix columns are visible to every q block
    block_needed = jnp.logical_or(
        jnp.logical_not(causal), i * block_q + block_q - 1 >= j * block_k
    )
    if prefix:
        block_needed = jnp.logical_or(
            block_needed, j * block_k < prefix_ref[0, 0, 0]
        )

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, 0, :]  # [Bq]
        delta = delta_ref[0, 0, 0, :]  # [Bq]
        p = _recompute_p(
            q, k, lse, scale=scale, causal=causal,
            i=i, j=j, block_q=block_q, block_k=block_k,
            seg_q=seg_q_ref[0, 0, 0, :] if segmented else None,
            seg_k=seg_k_ref[0, 0, 0, :] if segmented else None,
            prefix_len=prefix_ref[0, 0, 0] if prefix else None,
        )
        p_lo = p.astype(do.dtype)
        # dv += p^T do  : contract over the q rows
        dv_scratch[:] = dv_scratch[:] + jax.lax.dot_general(
            p_lo, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dp = do v^T  : [Bq, Bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        # dk += ds^T q
        dk_scratch[:] = dk_scratch[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(g == ng - 1, i == nq - 1))
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scratch[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    *rest,  # (+seg refs / prefix_ref per mode) dq_ref, dq_scratch
    scale: float, causal: bool, block_q: int, block_k: int,
    segmented: bool = False, prefix: bool = False,
):
    prefix_ref = seg_q_ref = seg_k_ref = None
    if segmented:
        seg_q_ref, seg_k_ref, dq_ref, dq_scratch = rest
    elif prefix:
        prefix_ref, dq_ref, dq_scratch = rest
    else:
        dq_ref, dq_scratch = rest
    i = pl.program_id(2)  # q block index
    j = pl.program_id(3)  # k block index (innermost, sequential)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    block_needed = jnp.logical_or(
        jnp.logical_not(causal), j * block_k <= i * block_q + block_q - 1
    )
    if prefix:
        block_needed = jnp.logical_or(
            block_needed, j * block_k < prefix_ref[0, 0, 0]
        )

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, 0, :]
        delta = delta_ref[0, 0, 0, :]
        p = _recompute_p(
            q, k, lse, scale=scale, causal=causal,
            i=i, j=j, block_q=block_q, block_k=block_k,
            seg_q=seg_q_ref[0, 0, 0, :] if segmented else None,
            seg_k=seg_k_ref[0, 0, 0, :] if segmented else None,
            prefix_len=prefix_ref[0, 0, 0] if prefix else None,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        # dq += ds k
        dq_scratch[:] = dq_scratch[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scratch[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, out, lse, do, dlse, *, causal, scale,
                    block_q, block_k, interpret, segment_ids=None,
                    segment_ids_kv=None, prefix_len=None):
    """Pallas backward: a dKV kernel (k blocks outer, q inner) and a dQ
    kernel (q outer, k inner), both recomputing probability tiles from the
    saved logsumexp — peak extra memory is O(Bq * Bk), never O(S^2).

    The lse cotangent is exact and free: d(lse)/d(scores) is the prob
    tile itself, so it enters as ``ds = p * (dp - (delta - dlse))`` —
    the existing delta term with ``dlse`` subtracted."""
    scale_v, interp = _resolve(scale, q.shape[-1], interpret)

    batch, heads, s_q, d = q.shape
    s_k = k.shape[2]
    group = _group_size(q, k)
    bq = _fit_block(block_q, s_q)
    bk = _fit_block(block_k, s_k)
    _check_mosaic_lane_block(interp, bq, s_q, "block_q")
    if segment_ids is not None:
        _check_mosaic_lane_block(interp, bk, s_k, "block_k")
    segmented = segment_ids is not None
    prefixed = prefix_len is not None

    f32 = jnp.float32
    delta = jnp.sum(
        do.astype(f32) * out.astype(f32), axis=-1
    ) - dlse.astype(f32)  # [B,H,Sq]
    # [B, H, 1, S] layout so the last-two block dims obey TPU tiling
    lse4 = lse.reshape(batch, heads, 1, s_q)
    delta4 = delta.reshape(batch, heads, 1, s_q)
    seg4q = (segment_ids.astype(jnp.int32).reshape(batch, 1, 1, s_q)
             if segmented else None)
    seg4k = None
    if segmented:
        seg_kv = (segment_ids_kv if segment_ids_kv is not None
                  else segment_ids)
        seg4k = seg_kv.astype(jnp.int32).reshape(batch, 1, 1, s_k)
    # [B, 1, LANES]: see the forward's prefix operand comment
    p2 = (jnp.broadcast_to(prefix_len.astype(jnp.int32)[:, None, None],
                           (batch, 1, LANES))
          if prefixed else None)

    # dKV grid (b, kv_head, j, g, i): g sweeps the query heads sharing
    # this KV head, i sweeps q blocks; both are sequential on TPU so the
    # f32 scratch accumulates across the whole group (the GQA head-sum).
    qh = lambda b, hk, j, g, i: (b, hk * group + g, i, 0)  # noqa: E731
    kvh = lambda b, hk, j, g, i: (b, hk, j, 0)  # noqa: E731
    row = lambda b, hk, j, g, i: (b, hk * group + g, 0, i)  # noqa: E731
    dkv_specs = [
        pl.BlockSpec((1, 1, bq, d), qh),
        pl.BlockSpec((1, 1, bk, d), kvh),
        pl.BlockSpec((1, 1, bk, d), kvh),
        pl.BlockSpec((1, 1, bq, d), qh),
        pl.BlockSpec((1, 1, 1, bq), row),
        pl.BlockSpec((1, 1, 1, bq), row),
    ]
    dkv_operands = [q, k, v, do, lse4, delta4]
    if segmented:
        dkv_specs.append(pl.BlockSpec(
            (1, 1, 1, bq), lambda b, hk, j, g, i: (b, 0, 0, i)))
        dkv_specs.append(pl.BlockSpec(
            (1, 1, 1, bk), lambda b, hk, j, g, i: (b, 0, 0, j)))
        dkv_operands += [seg4q, seg4k]
    if prefixed:
        dkv_specs.append(pl.BlockSpec(
            (1, 1, LANES), lambda b, hk, j, g, i: (b, 0, 0)))
        dkv_operands.append(p2)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale_v, causal=causal,
            block_q=bq, block_k=bk, segmented=segmented,
            prefix=prefixed,
        ),
        grid=(batch, k.shape[1], s_k // bk, group, s_q // bq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), kvh),
            pl.BlockSpec((1, 1, bk, d), kvh),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[_vmem((bk, d)), _vmem((bk, d))],
        interpret=interp,
    )(*dkv_operands)

    # dQ grid (b, h, i, j): per-q-head, reads the group's shared KV head
    qi = lambda b, h, i, j: (b, h, i, 0)  # noqa: E731
    kj = lambda b, h, i, j: (b, h // group, j, 0)  # noqa: E731
    ri = lambda b, h, i, j: (b, h, 0, i)  # noqa: E731
    dq_specs = [
        pl.BlockSpec((1, 1, bq, d), qi),
        pl.BlockSpec((1, 1, bk, d), kj),
        pl.BlockSpec((1, 1, bk, d), kj),
        pl.BlockSpec((1, 1, bq, d), qi),
        pl.BlockSpec((1, 1, 1, bq), ri),
        pl.BlockSpec((1, 1, 1, bq), ri),
    ]
    dq_operands = [q, k, v, do, lse4, delta4]
    if segmented:
        dq_specs.append(pl.BlockSpec(
            (1, 1, 1, bq), lambda b, h, i, j: (b, 0, 0, i)))
        dq_specs.append(pl.BlockSpec(
            (1, 1, 1, bk), lambda b, h, i, j: (b, 0, 0, j)))
        dq_operands += [seg4q, seg4k]
    if prefixed:
        dq_specs.append(pl.BlockSpec(
            (1, 1, LANES), lambda b, h, i, j: (b, 0, 0)))
        dq_operands.append(p2)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale_v, causal=causal,
            block_q=bq, block_k=bk, segmented=segmented,
            prefix=prefixed,
        ),
        grid=(batch, heads, s_q // bq, s_k // bk),
        in_specs=dq_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), qi),
        ],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[_vmem((bq, d))],
        interpret=interp,
    )(*dq_operands)[0]

    return dq, dk, dv


def _flash_attention_lse_bwd(causal, scale, block_q, block_k, interpret,
                             block_q_bwd, block_k_bwd, residuals,
                             cotangents):
    q, k, v, out, lse = residuals
    do, dlse = cotangents
    return _flash_backward(
        q, k, v, out, lse, do, dlse, causal=causal, scale=scale,
        block_q=block_q_bwd or block_q, block_k=block_k_bwd or block_k,
        interpret=interpret,
    )


flash_attention_lse.defvjp(
    _flash_attention_lse_fwd, _flash_attention_lse_bwd
)


# -- packed-sequence (segmented) flash attention ----------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def flash_attention_segmented(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,  # [B, H_kv, S, D]
    v: jax.Array,
    segment_ids: jax.Array,  # [B, S] int — tokens attend within segment
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
) -> jax.Array:
    """Flash attention over PACKED sequences: multiple documents share one
    row, separated by ``segment_ids``; tokens attend only within their
    segment (AND causally). The efficient alternative to padding — no
    wasted FLOPs on pad tokens, exact per-document attention.

    Role parity: the reference packs via attention-mask adapters on its
    CUDA kernels (``atorch/modules/transformer/layers.py:1095``
    ``flash_attn_with_mask_bias``); here the mask is fused into the
    Pallas tiles, never materializing S x S."""
    del block_q_bwd, block_k_bwd  # backward-only (vjp reads them)
    out, _lse = _flash_seg_fwd_impl(
        q, k, v, segment_ids, causal, scale, block_q, block_k, interpret
    )
    return out


def _flash_seg_fwd_impl(q, k, v, segment_ids, causal, scale, block_q,
                        block_k, interpret):
    scale_v, interp = _resolve(scale, q.shape[-1], interpret)
    out, lse = _flash_forward(
        q, k, v, scale=scale_v, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interp,
        segment_ids=segment_ids,
    )
    return out, lse.reshape(q.shape[0], q.shape[1], q.shape[2])


def _flash_seg_fwd(q, k, v, segment_ids, causal, scale, block_q, block_k,
                   interpret, block_q_bwd=0, block_k_bwd=0):
    out, lse = _flash_seg_fwd_impl(
        q, k, v, segment_ids, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, segment_ids, out, lse)


def _flash_seg_bwd(causal, scale, block_q, block_k, interpret,
                   block_q_bwd, block_k_bwd, residuals, do):
    import numpy as np

    q, k, v, segment_ids, out, lse = residuals
    dlse = jnp.zeros_like(lse)
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, do, dlse, causal=causal, scale=scale,
        block_q=block_q_bwd or block_q, block_k=block_k_bwd or block_k,
        interpret=interpret, segment_ids=segment_ids,
    )
    # integer primal: cotangent is float0 (no gradient flows to ids)
    dseg = np.zeros(segment_ids.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dseg


flash_attention_segmented.defvjp(_flash_seg_fwd, _flash_seg_bwd)


# NB: no single-array segmented-lse variant exists — ring attention's
# pair variant below with seg_q == seg_k subsumes it, and keeping two
# vjps in sync with _flash_backward bought nothing.


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11)
)
def flash_attention_segmented_pair_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    seg_q: jax.Array,  # [B, S_q]
    seg_k: jax.Array,  # [B, S_k] — independent kv-side ids
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
):
    """Segmented flash where the q-side and kv-side segment ids are
    INDEPENDENT arrays — the ring-attention step shape (local queries
    against a visiting KV shard). Returns (out, lse)."""
    del block_q_bwd, block_k_bwd  # backward-only (vjp reads them)
    return _flash_seg_pair_impl(
        q, k, v, seg_q, seg_k, causal, scale, block_q, block_k, interpret
    )


def _flash_seg_pair_impl(q, k, v, seg_q, seg_k, causal, scale, block_q,
                         block_k, interpret):
    scale_v, interp = _resolve(scale, q.shape[-1], interpret)
    out, lse = _flash_forward(
        q, k, v, scale=scale_v, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interp,
        segment_ids=seg_q, segment_ids_kv=seg_k,
    )
    return out, lse.reshape(q.shape[0], q.shape[1], q.shape[2])


def _flash_seg_pair_fwd(q, k, v, seg_q, seg_k, causal, scale, block_q,
                        block_k, interpret, block_q_bwd=0,
                        block_k_bwd=0):
    out, lse = _flash_seg_pair_impl(
        q, k, v, seg_q, seg_k, causal, scale, block_q, block_k, interpret
    )
    return (out, lse), (q, k, v, seg_q, seg_k, out, lse)


def _flash_seg_pair_bwd(causal, scale, block_q, block_k, interpret,
                        block_q_bwd, block_k_bwd, residuals,
                        cotangents):
    import numpy as np

    q, k, v, seg_q, seg_k, out, lse = residuals
    do, dlse = cotangents
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, do, dlse, causal=causal, scale=scale,
        block_q=block_q_bwd or block_q, block_k=block_k_bwd or block_k,
        interpret=interpret, segment_ids=seg_q, segment_ids_kv=seg_k,
    )
    f0 = jax.dtypes.float0
    return (dq, dk, dv, np.zeros(seg_q.shape, f0),
            np.zeros(seg_k.shape, f0))


flash_attention_segmented_pair_lse.defvjp(_flash_seg_pair_fwd,
                                          _flash_seg_pair_bwd)


# -- prefix-LM flash attention ----------------------------------------------


def flash_attention_prefix(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    prefix_len: jax.Array,  # [B] int — bidirectional over [0, prefix)
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
) -> jax.Array:
    """Prefix-LM flash attention (GLM's mask): token ``i`` attends key
    ``j`` iff ``j <= i`` (causal) OR ``j < prefix_len`` (the prompt is
    bidirectionally visible). Fused into the Pallas tiles — the GLM
    family's alternative to materializing an S x S bias. Reference
    counterpart: ``fa2_with_glm_mask``
    (``atorch/modules/transformer/layers.py:1191``).

    Thin wrapper over ``flash_attention_prefix_lse`` (single-vjp
    discipline: a dropped lse output has a zero cotangent, giving the
    identical backward — see the segmented variants' note)."""
    return flash_attention_prefix_lse(
        q, k, v, prefix_len, scale, block_q, block_k, interpret,
        block_q_bwd, block_k_bwd,
    )[0]


def _flash_prefix_fwd_impl(q, k, v, prefix_len, scale, block_q, block_k,
                           interpret):
    scale_v, interp = _resolve(scale, q.shape[-1], interpret)
    out, lse = _flash_forward(
        q, k, v, scale=scale_v, causal=True,
        block_q=block_q, block_k=block_k, interpret=interp,
        prefix_len=prefix_len,
    )
    return out, lse.reshape(q.shape[0], q.shape[1], q.shape[2])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def flash_attention_prefix_lse(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    prefix_len: jax.Array,  # [B] int — bidirectional over [0, prefix)
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
):
    """``flash_attention_prefix`` returning ``(out, lse)``,
    differentiable in both — the prefix-LM counterpart of
    ``flash_attention_lse``, needed wherever per-shard outputs merge by
    logsumexp (the sequence-parallel prefix ring)."""
    del block_q_bwd, block_k_bwd  # backward-only (vjp reads them)
    return _flash_prefix_fwd_impl(
        q, k, v, prefix_len, scale, block_q, block_k, interpret
    )


def _flash_prefix_lse_fwd(q, k, v, prefix_len, scale, block_q, block_k,
                          interpret, block_q_bwd=0, block_k_bwd=0):
    out, lse = _flash_prefix_fwd_impl(
        q, k, v, prefix_len, scale, block_q, block_k, interpret
    )
    return (out, lse), (q, k, v, prefix_len, out, lse)


def _flash_prefix_lse_bwd(scale, block_q, block_k, interpret,
                          block_q_bwd, block_k_bwd, residuals,
                          cotangents):
    import numpy as np

    q, k, v, prefix_len, out, lse = residuals
    do, dlse = cotangents
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, do, dlse, causal=True, scale=scale,
        block_q=block_q_bwd or block_q, block_k=block_k_bwd or block_k,
        interpret=interpret, prefix_len=prefix_len,
    )
    dprefix = np.zeros(prefix_len.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dprefix


flash_attention_prefix_lse.defvjp(_flash_prefix_lse_fwd,
                                  _flash_prefix_lse_bwd)


def segmented_attention(q, k, v, segment_ids, use_flash: bool,
                        block_q: int = 512, block_k: int = 1024,
                        interpret: Optional[bool] = None,
                        block_q_bwd: int = 0,
                        block_k_bwd: int = 0) -> jax.Array:
    """The one segmented-attention dispatch every model family shares:
    fused Pallas kernel (shard_map-routed) when flash is on, additive
    bias over the XLA reference otherwise. Centralized so the mask
    semantics cannot drift between families."""
    if use_flash:
        return flash_attention_segmented_auto(
            q, k, v, segment_ids, causal=True,
            block_q=block_q, block_k=block_k, interpret=interpret,
            block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
        )
    same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
    bias = jnp.where(same, 0.0, jnp.finfo(jnp.float32).min)
    return mha_reference(q, k, v, causal=True, bias=bias)


def flash_attention_prefix_auto(
    q, k, v, prefix_len,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
) -> jax.Array:
    """Multi-chip-safe ``flash_attention_prefix`` (same shard_map
    discipline as the other auto wrappers; prefix lengths shard along
    batch only)."""
    mesh = ambient_shard_mesh()
    if mesh is None:
        return flash_attention_prefix(
            q, k, v, prefix_len, scale, block_q, block_k, interpret
        )

    def body(ql, kl, vl, pl_):
        return flash_attention_prefix(
            ql, kl, vl, pl_, scale, block_q, block_k, interpret
        )

    return _shard_mapped_attention(
        mesh, body, q, k, v, extras=(prefix_len,), extra_ndims=(1,),
        batch_axes=batch_axes, head_axis=head_axis,
    )


def attention(q, k, v, causal=True, scale=None, use_flash=True, **kwargs):
    """Dispatch: Pallas flash kernel on TPU; XLA reference elsewhere (the
    interpreter-mode kernel is orders of magnitude slower than XLA on
    CPU/GPU, so it is only used when explicitly requested via kwargs)."""
    on_tpu = jax.default_backend() == "tpu"
    if use_flash and (on_tpu or kwargs.get("interpret")):
        return flash_attention(q, k, v, causal, scale, **kwargs)
    return mha_reference(q, k, v, causal=causal, scale=scale)
