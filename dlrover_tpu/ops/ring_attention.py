"""Ring attention: sequence-parallel attention over a mesh axis.

Role parity: ``atorch/atorch/modules/distributed_transformer/
distributed_attention.py:21-130`` (DistributedSoftmax + micro-chunk
allgather with compute/comm overlap on two CUDA streams). The TPU-native
formulation inverts the data movement: K/V shards rotate around the "seq"
mesh axis with ``lax.ppermute`` (one ICI hop per step — the natural TPU
torus pattern) while Q stays resident, and softmax is combined *online*
(running max/normalizer per query) so no [S, S] tile and no second pass
over the sequence ever exist. XLA overlaps the ppermute with the block
attention compute, which is the dual-stream overlap of the reference.

Memory per chip: O(S_local * D). Sequence length scales linearly with the
"seq" axis size.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_attend(q, k, v, row_offset, col_offset, scale, causal):
    """One (local-q x visiting-kv) block with global-position masking.

    Returns (unnormalized acc, row max m, row normalizer l).
    """
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        rows = lax.broadcasted_iota(jnp.int32, s.shape, 2) + row_offset
        cols = lax.broadcasted_iota(jnp.int32, s.shape, 3) + col_offset
        s = jnp.where(rows >= cols, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,Sq,1]
    # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1; clamp m first
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(m <= NEG_INF / 2, 0.0, p)  # kill fully-masked rows
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return acc, jnp.where(m <= NEG_INF / 2, NEG_INF, m), l


def ring_attention_local(
    q: jax.Array,  # local shard [B, H, S_local, D]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """The per-device body; call inside shard_map over ``axis_name``.

    Sequence layout is contiguous: device i owns global positions
    [i * S_local, (i+1) * S_local).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = q.shape[2]
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)

    qf = q.astype(jnp.float32)
    row_offset = my * s_local

    def combine(acc, m, l, a_new, m_new, l_new):
        m_comb = jnp.maximum(m, m_new)
        alpha = jnp.exp(m - m_comb)
        beta = jnp.exp(m_new - m_comb)
        return (
            acc * alpha + a_new * beta,
            m_comb,
            l * alpha + l_new * beta,
        )

    # step 0: the local block (no rotation needed)
    acc, m, l = _block_attend(
        qf, k, v, row_offset, my * s_local, scale, causal
    )

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        acc, m, l, cur_k, cur_v, owner = carry
        # rotate kv to the next neighbor (single ICI hop), then attend;
        # n-1 rotations total — the last visiting shard is not re-sent.
        cur_k = lax.ppermute(cur_k, axis_name, perm)
        cur_v = lax.ppermute(cur_v, axis_name, perm)
        owner = jnp.asarray((owner - 1) % n, jnp.int32)
        a_new, m_new, l_new = _block_attend(
            qf, cur_k, cur_v, row_offset, owner * s_local, scale, causal
        )
        acc, m, l = combine(acc, m, l, a_new, m_new, l_new)
        return (acc, m, l, cur_k, cur_v, owner), None

    (acc, m, l, _, _, _), _ = lax.scan(
        step, (acc, m, l, k, v, jnp.asarray(my, jnp.int32)), None,
        length=n - 1,
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # global [B, H, S, D], S sharded on `axis_name`
    k: jax.Array,
    v: jax.Array,
    mesh,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
) -> jax.Array:
    """shard_map wrapper: global arrays in, global arrays out.

    Composes with the surrounding GSPMD program: batch stays sharded on the
    data axes, heads on the tensor axis, sequence on the ring axis.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(batch_axes, head_axis, axis_name, None)
    fn = shard_map(
        functools.partial(
            ring_attention_local, axis_name=axis_name, causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
