"""Ring attention: sequence-parallel flash attention over a mesh axis.

Role parity: ``atorch/atorch/modules/distributed_transformer/
distributed_attention.py:21-130`` (DistributedSoftmax + micro-chunk
allgather with compute/comm overlap on two CUDA streams). The TPU-native
formulation inverts the data movement: K/V shards rotate around the "seq"
mesh axis with ``lax.ppermute`` (one ICI hop per step — the natural TPU
torus pattern) while Q stays resident, and per-step outputs are merged
*online* via their logsumexp, so no [S, S] tile and no second pass over
the sequence ever exist. XLA overlaps the ppermute with the block
attention compute, which is the dual-stream overlap of the reference.

Each ring step runs the in-tree Pallas flash kernel
(``ops.flash_attention.flash_attention_lse``) on the visiting K/V shard:
the [Bq, Bk] logits tile exists only in VMEM inside the kernel, and the
kernel returns ``(out, lse)`` which the ring merges exactly:

  lse' = logaddexp(lse, lse_i)
  o'   = o * exp(lse - lse') + o_i * exp(lse_i - lse')

Causality is resolved at *block* granularity, for free: the local shard
attends with the standard causal kernel; a visiting shard is either
entirely in the past (attend with no mask) or entirely in the future
(skip — ``lax.cond`` keeps the carry). GQA rotates only the KV heads
(``[B, H_kv, S_local, D]``), so ring ICI bytes are ``kv/h`` of the MHA
equivalent and the kernel indexes the shared KV head per query group.

Memory per chip: O(S_local * D). Sequence length scales linearly with
the "seq" axis size.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dlrover_tpu.ops.flash_attention import flash_attention_lse
from dlrover_tpu.ops.ring import ring_axis_size, ring_shift

NEG_INF = float(jnp.finfo(jnp.float32).min)


def ambient_ring_mesh(axis_name: str = "seq"):
    """The ambient mesh (``jax.sharding.set_mesh`` — what ``accelerate``
    establishes while tracing) when it carries a non-trivial
    ``axis_name`` axis that is NOT already manual; else None.

    This is what lets a model config say just ``seq_axis="seq"`` with
    ``mesh=None`` and stay ELASTIC-SAFE: a mesh frozen into the config
    at startup would survive ``on_world_change``'s re-accelerate and
    make the ring shard_map reference departed devices, while the
    ambient mesh is rebuilt with each accelerate. A manual (already
    inside shard_map) seq axis returns None so the caller falls back to
    ``ring_attention_local`` — the body form — instead of illegally
    nesting shard_maps. Both jax eras (``set_mesh`` abstract mesh, or
    the legacy ``with mesh:`` thread-resources context) resolve through
    ``shard_compat.ambient_mesh_with_axes``."""
    from dlrover_tpu.ops.shard_compat import ambient_mesh_with_axes

    return ambient_mesh_with_axes((axis_name,))


def impl_from_flags(use_flash: bool, flash_interpret) -> Optional[str]:
    """Map a model config's flash knobs onto the ring impl selector —
    THE one mapping every family shares: use_flash=False -> blockwise
    XLA; flash_interpret=True -> interpreted Pallas; flash_interpret=
    False -> FORCE Mosaic (the AOT contract: tracing on a CPU host for
    a TPU topology, where a backend sniff would silently pick the XLA
    attend whose autodiff backward stacks O(S^2) probability tiles
    across the ring scan); None -> auto (Mosaic on TPU, the blockwise
    XLA attend elsewhere)."""
    if not use_flash:
        return "xla"
    if flash_interpret:
        return "pallas_interpret"
    if flash_interpret is False:
        return "pallas"
    return None


def _xla_attend_lse(q, k, v, *, causal: bool, scale: float,
                    block_k: int = 512, seg_q=None, seg_k=None,
                    prefix=None):
    """Blockwise-XLA attention returning ``(out_f32, lse_f32)``.

    The non-TPU counterpart of the Pallas kernel: a ``lax.scan`` over
    K/V chunks carrying (acc, m, l), so peak memory is O(S_q * block_k)
    per head — linear in the sequence, like the kernel, which keeps the
    CPU-mesh long-context tests honest. GQA-aware (k/v may carry fewer
    heads). ``prefix`` [B]: keys with column < prefix are visible to
    EVERY query (OR-ed with the causal mask when ``causal`` — the
    prefix-LM rule; with causal=False it is the pure column-bound mask
    the prefix ring uses on wholly-future shards).
    """
    if seg_q is not None and seg_k is None:
        # self-attention shape: one id array serves both sides — never
        # fall through to the dummy carry, which would silently mask
        # every nonzero-segment token against everything
        seg_k = seg_q
    b, h, s_q, d = q.shape
    hkv, s_k = k.shape[1], k.shape[2]
    g = h // hkv
    bk = min(block_k, s_k)
    pad = (-s_k) % bk
    if pad:  # pad K/V with masked keys instead of shrinking the block
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if seg_k is not None:
            # sentinel no real query segment carries
            seg_k = jnp.pad(seg_k, ((0, 0), (0, pad)),
                            constant_values=-2)
    nk = (s_k + pad) // bk

    qf = q.reshape(b, hkv, g, s_q, d).astype(jnp.float32)
    kb = jnp.moveaxis(k.reshape(b, hkv, nk, bk, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, hkv, nk, bk, d), 2, 0)
    sb = (jnp.moveaxis(seg_k.reshape(b, nk, bk), 1, 0)
          if seg_k is not None else jnp.zeros((nk, b, 1), jnp.int32))

    def step(carry, inp):
        acc, m, l = carry
        kj, vj, sj, j = inp
        s = jnp.einsum(
            "bkgqd,bkcd->bkgqc", qf, kj.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        cols = lax.broadcasted_iota(jnp.int32, s.shape, 4) + j * bk
        if causal or prefix is not None:
            rows = lax.broadcasted_iota(jnp.int32, s.shape, 3)
            allowed = (rows >= cols) if causal else jnp.zeros(
                s.shape, bool)
            if prefix is not None:
                allowed = jnp.logical_or(
                    allowed,
                    cols < prefix[:, None, None, None, None],
                )
            s = jnp.where(allowed, s, NEG_INF)
        if pad:
            s = jnp.where(cols < s_k, s, NEG_INF)
        if seg_q is not None:
            # packed documents: mask cross-segment pairs
            same = (seg_q[:, None, None, :, None]
                    == sj[:, None, None, None, :])
            s = jnp.where(same, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where((s <= NEG_INF / 2)[..., :], 0.0, p)
        alpha = jnp.where(
            m <= NEG_INF / 2, 0.0, jnp.exp(m - m_safe)
        )
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bkgqc,bkcd->bkgqd", p, vj.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return (acc_new, m_new, l_new), None

    # derive init from qf so the carry varies over any shard_map manual
    # axes exactly like the step outputs do
    init = (
        qf * 0.0,
        qf[..., 0] * 0.0 + NEG_INF,
        qf[..., 0] * 0.0,
    )
    (acc, m, l), _ = lax.scan(
        step, init, (kb, vb, sb, jnp.arange(nk, dtype=jnp.int32))
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).reshape(b, h, s_q, d)
    lse = jnp.where(
        l == 0.0, NEG_INF, jnp.maximum(m, NEG_INF / 2) + jnp.log(l_safe)
    ).reshape(b, h, s_q)
    return out, lse


def _attend_lse(q, k, v, *, causal, scale, impl, block_q, block_k,
                seg_q=None, seg_k=None, block_q_bwd=0, block_k_bwd=0,
                prefix=None):
    """One (local-q x visiting-kv) shard attention -> (out f32, lse f32).

    ``prefix`` [B] (shard-local): with ``causal`` it is the prefix-LM
    rule (visible iff j <= i OR j < prefix — the ring's DIAGONAL
    shard); with causal=False it is the pure column bound (visible iff
    j < prefix — a wholly-FUTURE shard whose prompt columns are
    bidirectionally visible)."""
    if impl == "xla":
        return _xla_attend_lse(q, k, v, causal=causal, scale=scale,
                               block_k=block_k, seg_q=seg_q,
                               seg_k=seg_k, prefix=prefix)
    # "pallas" must pin interpret=False: under AOT the host backend is
    # CPU and the _resolve sniff would lower the interpreter emulation
    # into a TPU executable
    interp = True if impl == "pallas_interpret" else False
    if prefix is not None:
        if causal:
            from dlrover_tpu.ops.flash_attention import (
                flash_attention_prefix_lse,
            )

            out, lse = flash_attention_prefix_lse(
                q, k, v, prefix, scale, block_q, block_k, interp,
                block_q_bwd, block_k_bwd,
            )
            return out.astype(jnp.float32), lse
        # column-bound-only mask, no new kernel: the pair-segmented
        # kernel with q-side ids all 0 and k-side ids 0 iff visible
        from dlrover_tpu.ops.flash_attention import (
            flash_attention_segmented_pair_lse,
        )

        cols = jnp.arange(k.shape[2], dtype=jnp.int32)
        seg_kp = (cols[None, :] >= prefix[:, None]).astype(jnp.int32)
        seg_q0 = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        out, lse = flash_attention_segmented_pair_lse(
            q, k, v, seg_q0, seg_kp, False, scale, block_q, block_k,
            interp, block_q_bwd, block_k_bwd,
        )
        return out.astype(jnp.float32), lse
    if seg_q is not None:
        # ring steps attend local q against a VISITING kv shard: the two
        # sides carry independent segment arrays
        from dlrover_tpu.ops.flash_attention import (
            flash_attention_segmented_pair_lse,
        )

        out, lse = flash_attention_segmented_pair_lse(
            q, k, v, seg_q, seg_k, causal, scale, block_q, block_k,
            interp, block_q_bwd, block_k_bwd,
        )
        return out.astype(jnp.float32), lse
    out, lse = flash_attention_lse(
        q, k, v, causal, scale, block_q, block_k,
        interp, block_q_bwd, block_k_bwd,
    )
    return out.astype(jnp.float32), lse


def ring_attention_local(
    q: jax.Array,  # local shard [B, H, S_local, D]
    k: jax.Array,  # [B, H_kv, S_local, D]
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
    impl: Optional[str] = None,  # pallas | pallas_interpret | xla
    block_q: int = 512,
    block_k: int = 1024,
    segment_ids: Optional[jax.Array] = None,  # local [B, S_local]
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
    prefix_len: Optional[jax.Array] = None,  # [B] GLOBAL prefix length
) -> jax.Array:
    """The per-device body; call inside shard_map over ``axis_name``.

    Sequence layout is contiguous: device i owns global positions
    [i * S_local, (i+1) * S_local). With ``segment_ids``, packed
    documents may SPAN ring shards: the id arrays rotate with the KV
    shards (negligible ICI bytes next to KV) and every step masks
    cross-segment pairs.

    ``prefix_len`` (GLM's prefix-LM rule — visible iff j <= i OR
    j < prefix) decomposes over the ring exactly: a wholly-PAST
    visiting shard is fully visible (unchanged), the DIAGONAL shard
    runs the prefix kernel with the locally-shifted prefix, and a
    wholly-FUTURE shard contributes only its prompt columns
    (column-bound mask) — so unlike the causal ring, future shards are
    attended, not skipped. Requires ``causal=True`` and no
    ``segment_ids``.
    """
    n = ring_axis_size(axis_name)  # legacy-jax fallback in ops.ring
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    attend = functools.partial(
        _attend_lse, scale=scale, impl=impl,
        block_q=block_q, block_k=block_k,
        block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
    )
    seg = segment_ids
    merge = _merge_lse

    if prefix_len is not None:
        if not causal or seg is not None:
            raise ValueError(
                "prefix_len needs causal=True and no segment_ids "
                "(prefix-LM is a causal-family mask; packed prefix "
                "rows use the dense segmented path)"
            )
        return _ring_prefix(q, k, v, attend, prefix_len, axis_name,
                            n, my)

    # step 0: the local block — the only one needing an intra-block
    # causal mask, which the flash kernel applies at tile granularity
    o, lse = attend(q, k, v, causal=causal, seg_q=seg, seg_k=seg)

    def attend_merge(o, lse, ck, cv, cs):
        o_i, lse_i = attend(
            q, ck, cv, causal=False, seg_q=seg,
            seg_k=cs if seg is not None else None,
        )
        return merge(o, lse, o_i, lse_i)

    def step(carry, _):
        o, lse, cur_k, cur_v, cur_s, owner = carry
        # rotate kv to the next neighbor (single ICI hop, the shared
        # ops.ring step), then attend; n-1 rotations total — the last
        # visiting shard is not re-sent. Only the H_kv heads travel:
        # GQA pays kv/h of the MHA bytes.
        cur_k = ring_shift(cur_k, axis_name, n)
        cur_v = ring_shift(cur_v, axis_name, n)
        if seg is not None:
            cur_s = ring_shift(cur_s, axis_name, n)
        owner = jnp.asarray((owner - 1) % n, jnp.int32)
        if causal:
            # visiting shard is wholly past (attend, unmasked) or wholly
            # future (skip — keep the carry); never straddles the
            # diagonal because the layout is contiguous
            o, lse = lax.cond(
                owner < my,
                attend_merge,
                lambda o, lse, ck, cv, cs: (o, lse),
                o, lse, cur_k, cur_v, cur_s,
            )
        else:
            o, lse = attend_merge(o, lse, cur_k, cur_v, cur_s)
        return (o, lse, cur_k, cur_v, cur_s, owner), None

    init_seg = seg if seg is not None else jnp.zeros(
        (q.shape[0], 1), jnp.int32)
    (o, lse, _, _, _, _), _ = lax.scan(
        step, (o, lse, k, v, init_seg, jnp.asarray(my, jnp.int32)), None,
        length=n - 1,
    )
    return o.astype(q.dtype)


def _merge_lse(o, lse, o_i, lse_i):
    """The online-softmax merge — the numerical heart of the ring,
    shared by the causal and prefix bodies so their numerics can never
    fork. A fully-masked contribution (lse_i == -inf / NEG_INF) merges
    as an exact no-op."""
    lse_new = jnp.logaddexp(lse, lse_i)
    o_new = (
        o * jnp.exp(lse - lse_new)[..., None]
        + o_i * jnp.exp(lse_i - lse_new)[..., None]
    )
    return o_new, lse_new


def _ring_prefix(q, k, v, attend, prefix_len, axis_name, n, my):
    """The prefix-LM ring body (see ``ring_attention_local``)."""
    s_local = q.shape[2]
    p = prefix_len.astype(jnp.int32)

    # diagonal: causal OR locally-shifted prefix, fused in the kernel
    p_loc = jnp.clip(p - my * s_local, 0, s_local)
    o, lse = attend(q, k, v, causal=True, prefix=p_loc)

    def step(carry, _):
        o, lse, cur_k, cur_v, owner = carry
        cur_k = ring_shift(cur_k, axis_name, n)
        cur_v = ring_shift(cur_v, axis_name, n)
        owner = jnp.asarray((owner - 1) % n, jnp.int32)
        # p_vis: how many of the visiting shard's columns are prompt
        p_vis = jnp.clip(p - owner * s_local, 0, s_local)

        def past(o, lse, ck, cv):
            o_i, lse_i = attend(q, ck, cv, causal=False)
            return _merge_lse(o, lse, o_i, lse_i)

        def future(o, lse, ck, cv):
            # only the prompt columns are visible
            o_i, lse_i = attend(q, ck, cv, causal=False, prefix=p_vis)
            return _merge_lse(o, lse, o_i, lse_i)

        def visible(o, lse, ck, cv):
            return lax.cond(owner < my, past, future, o, lse, ck, cv)

        # a future shard wholly past the prompt (p_vis == 0 for every
        # batch row) contributes nothing — skip the kernel entirely,
        # like the causal ring skips future shards. The typical
        # long-context prefix batch (short prompt, long generation)
        # makes MOST ring steps skippable on most devices.
        o, lse = lax.cond(
            jnp.logical_or(owner < my, jnp.any(p_vis > 0)),
            visible,
            lambda o, lse, ck, cv: (o, lse),
            o, lse, cur_k, cur_v,
        )
        return (o, lse, cur_k, cur_v, owner), None

    (o, lse, _, _, _), _ = lax.scan(
        step, (o, lse, k, v, jnp.asarray(my, jnp.int32)), None,
        length=n - 1,
    )
    return o.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # global [B, H, S, D], S sharded on `axis_name`
    k: jax.Array,  # global [B, H_kv, S, D]
    v: jax.Array,
    mesh,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    impl: Optional[str] = None,
    block_q: int = 512,
    block_k: int = 1024,
    segment_ids: Optional[jax.Array] = None,  # global [B, S]
    block_q_bwd: int = 0,
    block_k_bwd: int = 0,
    prefix_len: Optional[jax.Array] = None,  # [B] global prefix length
) -> jax.Array:
    """shard_map wrapper: global arrays in, global arrays out.

    Composes with the surrounding GSPMD program: batch stays sharded on the
    data axes, heads on the tensor axis, sequence on the ring axis.
    ``segment_ids`` (packed documents, which may span ring shards) shard
    on (batch, seq) and rotate with the KV shards. ``prefix_len`` [B]
    (GLM prefix-LM) shards on batch only; see ``ring_attention_local``
    for the ring decomposition of the prefix mask.
    """
    from dlrover_tpu.ops.shard_compat import (
        get_shard_map,
        shard_map_check_kwargs,
    )

    shard_map = get_shard_map()

    if head_axis is not None:
        # GQA kv heads must still divide the head mesh axis; when they
        # don't (e.g. 8 kv heads over tensor=16), repeat minimally so
        # the spec is legal — still cheaper than the full h/kv repeat.
        # axis_sizes, not devices.shape: the mesh may be the ABSTRACT
        # ambient mesh (jax.sharding.get_abstract_mesh), which carries
        # sizes but no concrete device array
        tensor_size = dict(zip(mesh.axis_names, mesh.axis_sizes)).get(
            head_axis, 1
        )
        kv_heads, heads = k.shape[1], q.shape[1]
        if kv_heads % tensor_size:
            from dlrover_tpu.ops.flash_attention import minimal_kv_repeat

            rep = minimal_kv_repeat(kv_heads, heads, tensor_size)
            # No hidden bandwidth cliff (round-2 verdict #9): this costs
            # rep x the ring's ICI bytes, and the planner's seq-comm term
            # prices exactly this factor (planner.ring_kv_repeat).
            from dlrover_tpu.common.log import get_logger

            get_logger("ops.ring_attention").warning(
                "kv_heads=%d does not divide %s=%d: repeating kv x%d — "
                "ring ICI bytes grow %dx (planner prices this; prefer a "
                "tensor size dividing kv_heads)",
                kv_heads, head_axis, tensor_size, rep, rep,
            )
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
    spec = P(batch_axes, head_axis, axis_name, None)
    check_kw = shard_map_check_kwargs(shard_map)
    body = functools.partial(
        ring_attention_local, axis_name=axis_name, causal=causal,
        scale=scale, impl=impl, block_q=block_q, block_k=block_k,
        block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
    )
    if prefix_len is not None:
        if segment_ids is not None:
            raise ValueError(
                "prefix_len and segment_ids are mutually exclusive in "
                "the ring (packed prefix rows use the dense path)"
            )
        pl_spec = P(batch_axes)

        def prefix_body(ql, kl, vl, pl_):
            return body(ql, kl, vl, prefix_len=pl_)

        fn = shard_map(
            prefix_body, mesh=mesh,
            in_specs=(spec, spec, spec, pl_spec), out_specs=spec,
            **check_kw,
        )
        return fn(q, k, v, prefix_len.astype(jnp.int32))
    if segment_ids is not None:
        seg_spec = P(batch_axes, axis_name)

        def seg_body(ql, kl, vl, sl):
            return body(ql, kl, vl, segment_ids=sl)

        fn = shard_map(
            seg_body, mesh=mesh,
            in_specs=(spec, spec, spec, seg_spec), out_specs=spec,
            **check_kw,
        )
        return fn(q, k, v, segment_ids.astype(jnp.int32))
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **check_kw,
    )
    return fn(q, k, v)
