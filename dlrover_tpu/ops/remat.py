"""Rematerialization policies.

Role parity: ``atorch/auto/opt_lib/checkpoint_optimization.py`` (activation
checkpointing by module class) — on TPU this is ``jax.checkpoint`` with a
policy choosing what stays in HBM. The catalog maps the reference's
module-granular choices onto XLA-granular ones.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

def remat_enabled(policy) -> bool:
    """Single source of truth for 'does this policy value mean remat':
    shared by ``apply_remat`` and the models' pipeline ``remat_stage``
    plumbing so the per-layer and stage-boundary layers cannot disagree
    (e.g. on a falsy ``None`` policy)."""
    return bool(policy) and policy != "none"


def apply_remat(fn: Callable, policy: str = "dots_saveable",
                prevent_cse: bool = True) -> Callable:
    """Wrap a block function with a remat policy.

    ``policy`` is "none" (no remat), "full" (save nothing),
    "dots_and_attn_saveable" (dots + named Pallas attention outputs), or any
    ``jax.checkpoint_policies`` attribute name — "dots_saveable" (keep MXU
    outputs, recompute elementwise — the usual TPU sweet spot),
    "nothing_saveable", "dots_with_no_batch_dims_saveable", ...
    """
    if not remat_enabled(policy):
        return fn
    if policy == "full":
        return jax.checkpoint(fn, prevent_cse=prevent_cse)
    if policy == "attn_saveable":
        # save ONLY the named attention outputs: tiny residency
        # (B*S*D/layer) but the backward skips re-running the flash
        # kernel's forward — the selective middle ground between "full"
        # (8/6 recompute) and "dots_saveable" (which at multi-B scale
        # can overflow the compiler's memory budget)
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out"
            ),
            prevent_cse=prevent_cse,
        )
    if policy == "dots_and_attn_saveable":
        # dots_saveable only recognises dot_general outputs, so a Pallas
        # attention kernel would be re-run in the backward pass; saving
        # the named attention output avoids that recompute
        policy_fn = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        )
        return jax.checkpoint(fn, policy=policy_fn, prevent_cse=prevent_cse)
    policy_fn = getattr(jax.checkpoint_policies, policy, None)
    if not callable(policy_fn):
        available = sorted(
            n for n in dir(jax.checkpoint_policies) if not n.startswith("_")
        )
        raise ValueError(
            f"unknown remat policy {policy!r}; have 'none', 'full' or one "
            f"of {available}"
        )
    return jax.checkpoint(fn, policy=policy_fn, prevent_cse=prevent_cse)
