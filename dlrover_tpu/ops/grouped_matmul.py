"""Grouped matmul Pallas kernel — the dropless-MoE expert compute.

``y[i] = x[i] @ w[expert_of_row_i]`` where rows are SORTED by expert and
every expert's group is padded to a multiple of the row-tile, so each
row-tile belongs to exactly one expert. The per-tile expert index rides
scalar prefetch (``PrefetchScalarGridSpec``), and the kernel picks that
expert's weight block via the BlockSpec index map — no [T, E, C]
one-hot tensors, no capacity, no dropped tokens.

Role parity: the reference delegates its MoE hot path to a fused CUDA
backend (``atorch/atorch/modules/moe/moe_layer.py:511`` fastmoe); the
public megablocks line of work frames the same computation as
block-sparse "grouped GEMM". The TPU formulation here: tile-aligned
group padding costs at most ``E * (block_t - 1)`` pad rows — versus the
capacity approach's ``(factor - 1) * T`` padded slots PLUS dropped
overflow tokens — and the MXU sees plain dense [block_t, D] x
[D, block_f] tiles.

Backward is a custom VJP:
  dx = dy @ w[e]^T       — the same kernel over transposed weights;
  dw[e] = sum over e's tiles of x_tile^T @ dy_tile — an accumulation
  kernel whose grid runs row-tiles FASTEST so consecutive steps that
  share an expert keep the output block resident and accumulate
  (tiles of one expert are contiguous by construction, so no output
  block is ever revisited after being left).

Everything accumulates in f32 regardless of input dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_block(dim: int, want: int) -> int:
    """Largest tile <= ``want`` that divides ``dim``, preferring
    lane-aligned multiples of 128 (Mosaic's happy path); falls back to
    any divisor, then to ``dim`` itself."""
    want = min(want, dim)
    for cand in range(want - want % 128, 0, -128):
        if dim % cand == 0:
            return cand
    for cand in range(want, 0, -1):
        if dim % cand == 0:
            return cand
    return dim


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _fwd_kernel(tile_expert_ref, x_ref, w_ref, y_ref):
    del tile_expert_ref  # consumed by the index maps
    y_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(y_ref.dtype)


def _dw_kernel(tile_expert_ref, x_ref, dy_ref, dw_ref):
    i = pl.program_id(1)  # row-tile index (fastest grid dim)
    e_here = tile_expert_ref[i]
    e_prev = tile_expert_ref[jnp.maximum(i - 1, 0)]
    first = jnp.logical_or(i == 0, e_here != e_prev)
    contrib = jax.lax.dot_general(
        x_ref[...], dy_ref[...],
        (((0,), (0,)), ((), ())),  # [block_t, D]^T @ [block_t, F]
        preferred_element_type=jnp.float32,
    )

    @pl.when(first)
    def _init():
        dw_ref[0] = contrib.astype(dw_ref.dtype)

    @pl.when(jnp.logical_not(first))
    def _acc():
        dw_ref[0] = (dw_ref[0] + contrib).astype(dw_ref.dtype)


def _fwd_kernel_quant(tile_expert_ref, x_ref, s_ref, w_ref, y_ref):
    """The quantized-LHS forward kernel: dequantize the fp8 row tile
    IN KERNEL (one f32 multiply per element against the per-block
    scales riding their own tile) and run the same f32-accumulating
    dot. The multiply happens in f32 exactly like
    ``ops.quantize.dequantize_block_scaled``, so this kernel is bitwise
    equal to dequant-then-``_fwd_kernel`` — the oracle contract the
    tests pin."""
    del tile_expert_ref  # consumed by the index maps
    x = x_ref[...].astype(jnp.float32)
    s = s_ref[...].astype(jnp.float32)
    bt, d = x.shape
    nb = s.shape[1]
    x = (x.reshape(bt, nb, d // nb) * s[:, :, None]).reshape(bt, d)
    y_ref[...] = jax.lax.dot_general(
        x, w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(y_ref.dtype)


def _grouped_matmul_fwd_quant(values, scales, w, tile_expert, block_t,
                              block_f, interpret, out_dtype):
    tp, d = values.shape
    e, dw_, f = w.shape
    assert d == dw_, (values.shape, w.shape)
    assert tp % block_t == 0, (tp, block_t)
    nb = scales.shape[1]
    num_t = tp // block_t
    bf = _pick_block(f, block_f)
    num_f = f // bf

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_t, num_f),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j, te: (i, 0)),
            pl.BlockSpec((block_t, nb), lambda i, j, te: (i, 0)),
            pl.BlockSpec((1, d, bf), lambda i, j, te: (te[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((block_t, bf), lambda i, j, te: (i, j)),
    )
    return pl.pallas_call(
        _fwd_kernel_quant,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tp, f), out_dtype),
        interpret=interpret,
    )(tile_expert, values, scales, w)


def _grouped_matmul_fwd(x, w, tile_expert, block_t, block_f, interpret):
    tp, d = x.shape
    e, dw_, f = w.shape
    assert d == dw_, (x.shape, w.shape)
    assert tp % block_t == 0, (tp, block_t)
    num_t = tp // block_t
    bf = _pick_block(f, block_f)
    num_f = f // bf

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_t, num_f),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j, te: (i, 0)),
            pl.BlockSpec((1, d, bf), lambda i, j, te: (te[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((block_t, bf), lambda i, j, te: (i, j)),
    )
    return pl.pallas_call(
        _fwd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tp, f), x.dtype),
        interpret=interpret,
    )(tile_expert, x, w)


def _grouped_matmul_dw(x, dy, tile_expert, num_experts, block_t, block_f,
                       interpret):
    tp, d = x.shape
    _, f = dy.shape
    num_t = tp // block_t
    bf = _pick_block(f, block_f)
    num_f = f // bf

    # row-tiles FASTEST (innermost): consecutive steps sharing an expert
    # accumulate into the resident output block; a left block is never
    # revisited because each expert's tiles are contiguous
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_f, num_t),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda j, i, te: (i, 0)),
            pl.BlockSpec((block_t, bf), lambda j, i, te: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, d, bf), lambda j, i, te: (te[i], 0, j)),
    )
    return pl.pallas_call(
        _dw_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_experts, d, f), jnp.float32),
        interpret=interpret,
    )(tile_expert, x, dy)


def _check_tile_expert(tile_expert, num_experts: int):
    """Cheap debug-mode contract check, CONCRETE values only (a traced
    ``tile_expert`` — the jitted production path — skips it for free).

    The two contract violations it catches produce silent garbage on
    real TPU but NOT in interpret mode: the interpreter zero-fills
    pallas output buffers, so (a) an expert absent from ``tile_expert``
    reads back a zero dw block instead of the uninitialized garbage
    Mosaic would leave, and (b) a non-monotone ``tile_expert`` revisits
    a dw block the accumulation kernel already left, whose first-tile
    predicate then re-INITIALIZES it, silently dropping the earlier
    tiles' contributions.
    """
    if isinstance(tile_expert, jax.core.Tracer):
        return
    import numpy as np

    te = np.asarray(tile_expert)
    if te.size and np.any(np.diff(te) < 0):
        raise ValueError(
            "grouped_matmul: tile_expert must be NON-DECREASING (each "
            "expert's tiles contiguous) — the dw kernel accumulates "
            "into the resident output block and never revisits one; "
            f"got {te.tolist()}"
        )
    missing = sorted(set(range(num_experts)) - set(int(v) for v in te))
    if missing:
        raise ValueError(
            "grouped_matmul: every expert 0..E-1 must own at least one "
            f"row-tile, but experts {missing} are absent from "
            "tile_expert — their dw output blocks would be "
            "UNINITIALIZED garbage on real TPU (interpret mode "
            "zero-fills, masking the bug). Give each empty expert one "
            "sentinel tile of zero rows (see ops.moe._moe_compute_grouped)"
        )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def grouped_matmul(x, w, tile_expert, block_t=128, block_f=512,
                   interpret=None):
    """``y[i] = x[i] @ w[tile_expert[i // block_t]]``.

    Args:
      x: [Tp, D] rows sorted by expert, each expert's group padded to a
        multiple of ``block_t`` (pad rows may be garbage; their outputs
        are garbage and must be masked by the caller's un-sort).
      w: [E, D, F] per-expert weights.
      tile_expert: [Tp // block_t] int32, the expert owning each
        row-tile — every row in a tile MUST share the expert (the
        tile-aligned padding guarantees it). Two further contract
        requirements exist for the BACKWARD pass and are invisible in
        interpret mode (which zero-fills output buffers):
        * every expert 0..E-1 must appear at least once — an expert
          owning no tile leaves its dw output block UNINITIALIZED
          (garbage) on real TPU, because the accumulation grid never
          visits it. Callers give empty experts one sentinel tile of
          zero rows (``ops.moe._moe_compute_grouped``).
        * values must be NON-DECREASING (each expert's tiles
          contiguous) — the dw kernel initializes an expert's block on
          its first tile and accumulates while resident; a revisited
          block would be re-initialized, dropping earlier tiles.
        Concrete (non-traced) ``tile_expert`` values are validated at
        call time (``_check_tile_expert``); traced values are the
        caller's responsibility.
      interpret: None = auto (interpreter off TPU, Mosaic on TPU);
        False forces Mosaic (the deviceless-AOT contract).
    Returns [Tp, F] in x's dtype (f32 accumulation inside).
    """
    _check_tile_expert(tile_expert, w.shape[0])
    interp = _auto_interpret(interpret)
    return _grouped_matmul_fwd(x, w, tile_expert, block_t, block_f,
                               interp)


def _gm_fwd(x, w, tile_expert, block_t, block_f, interpret):
    y = grouped_matmul(x, w, tile_expert, block_t, block_f, interpret)
    return y, (x, w, tile_expert)


def _gm_bwd(block_t, block_f, interpret, res, dy):
    x, w, tile_expert = res
    interp = _auto_interpret(interpret)
    # dx: the same grouped product against w^T ([E, F, D])
    w_t = jnp.swapaxes(w, 1, 2)
    dx = _grouped_matmul_fwd(
        dy.astype(x.dtype), w_t, tile_expert, block_t, block_f, interp
    )
    dw = _grouped_matmul_dw(
        x, dy.astype(x.dtype), tile_expert, w.shape[0], block_t,
        block_f, interp
    ).astype(w.dtype)
    return dx.astype(x.dtype), dw, None


grouped_matmul.defvjp(_gm_fwd, _gm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def grouped_matmul_quantized(values, scales, w, tile_expert,
                             block_t=128, block_f=512, interpret=None,
                             out_dtype=jnp.float32):
    """``grouped_matmul`` over a BLOCK-SCALED fp8 LHS, dequantized IN
    KERNEL: ``y[i] = (values[i] * scales[i])  @ w[tile_expert[i //
    block_t]]`` where ``values`` is [Tp, D] e4m3 and ``scales`` is
    [Tp, D/block] f32 (``ops.quantize.quantize_block_scaled`` layout;
    pad rows carry zero values, so any scale decodes them to zero).

    The contract the tests pin: bitwise equal to
    ``grouped_matmul(dequantize_block_scaled(values, scales), w, ...)``
    — the dequant multiply runs in f32 inside the kernel exactly as the
    standalone decode does, so fusing it costs nothing numerically
    while the rows enter the kernel at wire precision (the point: the
    [Tp, D] buffer the exchange produced is never re-materialized at
    4x/2x the bytes just to feed the GEMM).

    Differentiable in ``w`` ONLY: ``dw[e] = dequant(values, scales)^T @
    dy`` through the same accumulation kernel as the unquantized path.
    ``values``/``scales`` get zero cotangents — they arrived over the
    wire already quantized; the activation gradient flows through the
    caller's wire boundary (``ops.moe``'s quantized exchange defines
    the straight-through chain), not through the encode.
    """
    interp = _auto_interpret(interpret)
    return _grouped_matmul_fwd_quant(values, scales, w, tile_expert,
                                     block_t, block_f, interp,
                                     out_dtype)


def _gmq_fwd(values, scales, w, tile_expert, block_t, block_f,
             interpret, out_dtype):
    y = grouped_matmul_quantized(values, scales, w, tile_expert,
                                 block_t, block_f, interpret, out_dtype)
    return y, (values, scales, w, tile_expert)


def _gmq_bwd(block_t, block_f, interpret, out_dtype, res, dy):
    from dlrover_tpu.ops.quantize import dequantize_block_scaled

    values, scales, w, tile_expert = res
    interp = _auto_interpret(interpret)
    x_deq = dequantize_block_scaled(values, scales, jnp.float32)
    dw = _grouped_matmul_dw(
        x_deq, dy.astype(x_deq.dtype), tile_expert, w.shape[0],
        block_t, block_f, interp,
    ).astype(w.dtype)
    return jnp.zeros_like(values), jnp.zeros_like(scales), dw, None


grouped_matmul_quantized.defvjp(_gmq_fwd, _gmq_bwd)
