"""Block-scaled fp8 quantization — the wire format of the low-precision
MoE dispatch.

The grouped_ep row exchange moves [P, n, D] token rows over ICI every
step (``ops.moe``); at the scales the fault-tolerant-HSDP line of work
targets (PAPERS.md 2602.00277) those wire bytes are the binding
resource. Block-scaled fp8 halves them: each row's channels split into
blocks of ``QUANT_BLOCK`` and every block ships as e4m3 values plus ONE
f32 scale — 1 byte/element of values and ``4 / block`` bytes/element of
scale side-band, ~0.56x of bf16 (the planner prices exactly this, see
``parallel.planner._moe_dispatch_terms``; the G106 audit verifies it on
the compiled HLO).

Why per-block rather than per-tensor scales: a single scale for the
whole exchange buffer is set by the largest outlier row, pushing every
other row into the bottom of e4m3's ~2-decimal-digit range; per-block
scales bound the quantization error by each 32-channel neighborhood
instead (the microscaling/MX convention). Why f32 scales: they ride a
side-band that is 1/32 of the payload — making them cheaper (e8m0)
saves ~1% of wire for a real accuracy cost.

Everything here is elementwise-per-row, which is the property the
exact-oracle tests lean on: quantization COMMUTES with the row
exchanges (an all_to_all/ppermute ring is a pure permutation of rows),
so quantize -> exchange -> dequantize is bitwise equal to the local
quantize -> dequantize reference with a full-precision wire
(``tests/test_quantize.py`` pins it fwd+bwd).

Zero blocks: an all-zero block would produce scale 0 and 0/0 values;
the scale clamps to 1.0 and the values quantize to exact zeros — pad
rows (the dispatch's zero sentinel) survive quantization untouched.
Denormals: a block whose max|x| sits below e4m3's smallest normal
up-scales into range (scale = amax / FP8_MAX < 1), so tiny-but-nonzero
blocks keep ~2 digits instead of flushing to zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# channels per scale block (the MX convention's 32); ``resolve_quant_block``
# shrinks it to the largest divisor of the channel dim
QUANT_BLOCK = 32

# e4m3fn: the widest-range fp8 (no inf, max 448) — activations/rows want
# range; e5m2 is the gradient format and the wire here carries rows and
# row-shaped cotangents, both activation-scaled
WIRE_DTYPE = jnp.float8_e4m3fn

FP8_MAX = float(jnp.finfo(WIRE_DTYPE).max)  # 448.0

# wire precisions the MoE dispatch understands (ops.moe resolves the
# knob): "bf16" = no quantization (the exchange carries the compute
# dtype); "fp8" = block-scaled e4m3 values + f32 scales on the wire;
# "fp8_qdq" = the REFERENCE ORACLE — quantize->dequantize applied
# locally at every wire crossing with the exchange itself left in full
# precision. Identical numbers to "fp8" by construction (quantization
# commutes with the row permutation), so it is what the exact fwd+bwd
# tests compare against, and a debug mode for isolating wire-transport
# issues from quantization numerics.
PRECISIONS = ("bf16", "fp8", "fp8_qdq")


def resolve_quant_block(channels: int, want: int = QUANT_BLOCK) -> int:
    """The largest divisor of ``channels`` that is <= ``want`` — scale
    blocks must tile the channel dim exactly (static shapes; a ragged
    tail block would need its own masked path for one block's worth of
    savings)."""
    want = max(1, min(int(want), int(channels)))
    for cand in range(want, 0, -1):
        if channels % cand == 0:
            return cand
    return 1


def quantize_block_scaled(x: jax.Array, block: int = 0):
    """``x [..., D]`` -> ``(values [..., D] e4m3, scales [..., D/block]
    f32)`` with ``dequantize_block_scaled(values, scales)`` the decode.

    Per block: ``scale = max|x| / FP8_MAX`` (so the block max lands on
    +-448, the top of e4m3's range), zero blocks clamp to scale 1.0
    (values quantize to exact zeros). The division happens in f32
    regardless of input dtype — the encode must not round twice.
    """
    d = x.shape[-1]
    b = block or resolve_quant_block(d)
    if d % b:
        raise ValueError(
            f"quantize_block_scaled: block {b} does not divide the "
            f"channel dim {d} (use resolve_quant_block)"
        )
    xb = x.astype(jnp.float32).reshape(x.shape[:-1] + (d // b, b))
    amax = jnp.max(jnp.abs(xb), axis=-1)  # [..., D/b]
    # the scale floors at the smallest NORMAL f32: on a
    # flush-to-zero backend (TPU) ``amax / FP8_MAX`` for a
    # deep-denormal block would flush to 0.0 and the division below
    # would mint inf -> NaN-in-e4m3; flooring keeps the encode finite
    # (such a block quantizes to zeros — below fp8's resolution
    # anyway) without touching any normal-range block
    scales = jnp.where(
        amax > 0,
        jnp.maximum(amax / FP8_MAX, jnp.finfo(jnp.float32).tiny),
        1.0,
    )
    values = (xb / scales[..., None]).astype(WIRE_DTYPE)
    return values.reshape(x.shape), scales


def dequantize_block_scaled(values: jax.Array, scales: jax.Array,
                            dtype=jnp.float32) -> jax.Array:
    """Decode: ``values * scales`` broadcast per block, in f32 (one
    exact multiply — e4m3 -> f32 is lossless and the scales are f32),
    cast to ``dtype`` last. The in-kernel dequant of
    ``ops.grouped_matmul.grouped_matmul_quantized`` computes exactly
    this product, which is what makes dequant-in-kernel bitwise equal
    to dequant-then-matmul (the oracle contract)."""
    d = values.shape[-1]
    nb = scales.shape[-1]
    vb = values.astype(jnp.float32).reshape(
        values.shape[:-1] + (nb, d // nb)
    )
    return (vb * scales[..., None]).reshape(values.shape).astype(dtype)


def qdq(x: jax.Array, block: int = 0) -> jax.Array:
    """quantize -> dequantize in place (f32 out): the local reference
    transform of the "fp8_qdq" oracle mode."""
    v, s = quantize_block_scaled(x, block)
    return dequantize_block_scaled(v, s)


# -- int8 storage (the serving tier's KV-cache format) -----------------------

# KV-cache storage precisions (``serving.kv_cache`` resolves the knob):
# "bf16"/"f32" = pages stored in the compute dtype; "int8" = pages
# stored as int8 values + f32 per-block scales (~1/4 of f32 residency —
# the decode regime is KV-READ memory-bound, so smaller pages are both
# capacity AND bandwidth). Like the wire formats above, int8 storage is
# judged by the G109 "kv" drift family, not trusted blindly.
KV_PRECISIONS = ("f32", "bf16", "int8")

INT8_MAX = 127.0


def quantize_block_scaled_int8(x: "jax.Array", block: int = 0):
    """``x [..., D]`` -> ``(values [..., D] int8, scales [..., D/block]
    f32)``; symmetric per-block scaling (``scale = max|x| / 127``), the
    same block geometry (and zero-block clamp) as the fp8 encode above.
    int8 rather than e4m3 for STORAGE: a KV page is written once and
    read every later decode step, so the format wants mantissa (int8's
    ~2.4 digits within a block) over dynamic range — the block scale
    already carries the range."""
    d = x.shape[-1]
    b = block or resolve_quant_block(d)
    if d % b:
        raise ValueError(
            f"quantize_block_scaled_int8: block {b} does not divide the "
            f"channel dim {d} (use resolve_quant_block)"
        )
    xb = x.astype(jnp.float32).reshape(x.shape[:-1] + (d // b, b))
    amax = jnp.max(jnp.abs(xb), axis=-1)
    scales = jnp.where(
        amax > 0,
        jnp.maximum(amax / INT8_MAX, jnp.finfo(jnp.float32).tiny),
        1.0,
    )
    # round-to-nearest, clamped: the encode must be deterministic and
    # saturating (an outlier exactly at amax lands on +-127)
    values = jnp.clip(
        jnp.round(xb / scales[..., None]), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)
    return values.reshape(x.shape), scales


def dequantize_block_scaled_int8(values: "jax.Array", scales: "jax.Array",
                                 dtype=jnp.float32) -> "jax.Array":
    """Decode: ``values * scales`` per block in f32 (int8 -> f32 is
    exact, scales are f32), cast last — the mirror of the fp8 decode."""
    d = values.shape[-1]
    nb = scales.shape[-1]
    vb = values.astype(jnp.float32).reshape(
        values.shape[:-1] + (nb, d // nb)
    )
    return (vb * scales[..., None]).reshape(values.shape).astype(dtype)


# gradient-path wire precisions (``parallel.accelerate``): unlike the
# dense gathers a quantized gradient is NOT dequant-exact training —
# the compression error must be carried forward ("fp8", error
# feedback) or it accumulates ("fp8_nofb", the degradation control the
# telescoping tests compare against; never train with it)
GRAD_PRECISIONS = ("bf16", "fp8", "fp8_nofb")


def error_feedback_qdq(g: jax.Array, residual: jax.Array,
                       feedback: bool = True):
    """One error-feedback quantization step on one gradient leaf:
    ``(g_quantized, new_residual)``.

    The residual (last step's decompression error, zeros at init) is
    added BACK into the gradient before quantizing, and the new
    residual is the error of THIS quantization — so across steps the
    errors telescope: sum(applied) = sum(raw grads) - final_residual,
    i.e. the optimizer eventually sees every gradient bit, just a step
    or two late (the classic EF-SGD/1-bit-Adam argument, and why the
    residual must ride TrainState through checkpoint and reshard).
    With ``feedback=False`` the raw gradient is quantized and the
    error is DROPPED — the control mode whose drift the tests pin as
    strictly worse."""
    if feedback:
        eff = g + residual.astype(g.dtype)
    else:
        eff = g
    v, s = quantize_block_scaled(eff)
    gq = dequantize_block_scaled(v, s, g.dtype)
    new_residual = (eff - gq if feedback
                    else jnp.zeros_like(residual))
    return gq, new_residual.astype(residual.dtype)
