"""Version-compat shims for the shard_map era, shared by every op.

jax moved these entry points across releases; each shim lives HERE once
so the ops (flash/ring attention, MoE expert parallelism) and the
pipeline constraints cannot drift:

  * ``get_shard_map()`` — ``jax.shard_map`` (>= 0.5) or the
    ``jax.experimental.shard_map`` original.
  * ``shard_map_check_kwargs()`` — pallas_call outputs carry no
    varying-mesh-axes metadata, so vma/replication checking cannot see
    through a kernel; the disable knob is ``check_vma`` on current jax
    and ``check_rep`` on older shard_map.
  * ``ambient_mesh()`` — the mesh the surrounding program established:
    ``jax.sharding.get_abstract_mesh()`` (the ``set_mesh`` era) when
    available, else the legacy thread-resources physical mesh (the
    ``with mesh:`` context ``parallel.accelerate`` falls back to on old
    jax). None when unsharded.
  * ``manual_axis_names()`` — axis names already bound *manually* (an
    enclosing shard_map/pmap): an ambient consumer must not build a
    nested shard_map over them. On the set_mesh era the abstract mesh
    carries ``axis_types``; on legacy jax the bound names show up in
    the tracing axis env.
"""

from __future__ import annotations

from typing import Optional, Set

import jax


def get_shard_map():
    try:
        from jax import shard_map  # jax >= 0.5
    except ImportError:
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_map_check_kwargs(shard_map=None) -> dict:
    import inspect

    if shard_map is None:
        shard_map = get_shard_map()
    params = inspect.signature(shard_map).parameters
    if "check_vma" in params:
        return {"check_vma": False}
    if "check_rep" in params:
        return {"check_rep": False}
    return {}


def ambient_mesh():
    """The ambient mesh, or None. No axis filtering here — callers
    layer their own relevance checks (axis presence, size, manualness)
    on top."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if tuple(getattr(mesh, "axis_names", ()) or ()):
            return mesh
    except (AttributeError, ValueError, TypeError):
        pass  # API absent (old jax) / no mesh context
    try:  # legacy jax: the "with mesh:" thread-resources context
        from jax._src import mesh as mesh_lib

        phys = mesh_lib.thread_resources.env.physical_mesh
        if phys is not None and not phys.empty:
            return phys
    except (ImportError, AttributeError):
        pass  # private module moved / no thread-resources mesh
    return None


def manual_axis_names(mesh=None, candidates=()) -> Set[str]:
    """The subset of ``candidates`` (mesh axis names) already manual in
    the current context."""
    names: Set[str] = set()
    if mesh is not None:
        try:
            types = dict(zip(mesh.axis_names, mesh.axis_types))
            names |= {
                a for a, t in types.items() if "manual" in str(t).lower()
            }
        except (AttributeError, TypeError, ValueError):
            pass  # axis_types absent on old jax
    for a in candidates:
        if a in names:
            continue
        try:
            # bound only inside an enclosing shard_map/pmap trace —
            # NameError otherwise (verified: plain, with-mesh, and jit
            # contexts all raise). The stray tracer is dead code.
            jax.lax.axis_index(a)
            names.add(a)
        except Exception:  # noqa: BLE001 — unbound: not manual
            pass
    return names


_FP8_WIRE_SUPPORTED: Optional[bool] = None


def fp8_wire_supported() -> bool:
    """Whether this backend can carry block-scaled fp8 on the wire:
    ``float8_e4m3fn`` exists and a tiny cast round-trip executes on the
    default backend. Probed ONCE per process (the result cannot change
    under a fixed jaxlib+backend); ``ops.moe`` falls back to the bf16
    wire — logged, never raised — when the probe fails, so a
    ``moe_precision=fp8`` config degrades instead of killing the job on
    an old toolchain."""
    global _FP8_WIRE_SUPPORTED
    if _FP8_WIRE_SUPPORTED is not None:
        return _FP8_WIRE_SUPPORTED
    try:
        import numpy as np

        import jax.numpy as jnp

        dt = jnp.float8_e4m3fn
        # the probe is usually reached at TRACE time (ops.moe resolves
        # the knob inside the jitted step): compile-time eval keeps the
        # round-trip off the ambient trace, concrete and checkable
        with jax.ensure_compile_time_eval():
            x = jnp.asarray(np.asarray([0.5, -448.0, 0.0], np.float32))
            back = jax.jit(
                lambda v: v.astype(dt).astype(jnp.float32))(x)
            jax.block_until_ready(back)
            _FP8_WIRE_SUPPORTED = bool(np.asarray(back)[0] == 0.5)
    except Exception:  # noqa: BLE001 — any failure = not supported
        import logging

        logging.getLogger("dlrover_tpu.ops.shard_compat").warning(
            "fp8 wire probe failed; quantized MoE precision will fall "
            "back to the bf16 wire", exc_info=True,
        )
        _FP8_WIRE_SUPPORTED = False
    return _FP8_WIRE_SUPPORTED


def ambient_mesh_with_axes(axes, min_size: int = 2) -> Optional[object]:
    """The ambient mesh when it carries every axis in ``axes``,
    none of them already manual, with combined size >= ``min_size``;
    else None."""
    import math

    mesh = ambient_mesh()
    if mesh is None:
        return None
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    if any(a not in names for a in axes):
        return None
    if manual_axis_names(mesh, candidates=axes):
        return None
    sizes = dict(zip(names, mesh.axis_sizes))
    if math.prod(sizes[a] for a in axes) < min_size:
        return None
    return mesh
