"""Reference (pure-XLA) attention used for correctness checks and as the
CPU fallback for the Pallas kernels."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def mha_reference(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,  # [B, H_kv, S, D] (H_kv divides H; GQA broadcast here)
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (head_dim ** 0.5)
    if k.shape[1] != q.shape[1]:  # GQA: the reference may materialize
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", probs.astype(v.dtype), v
    )
