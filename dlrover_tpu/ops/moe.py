"""Mixture-of-Experts: router, capacity-based dispatch, expert parallelism.

Role parity: ``atorch/atorch/modules/moe/moe_layer.py:22-565`` (expert
process groups + ``_AllToAll`` autograd + ``Experts``) and
``switch_gating.py:24-195`` (top-1 gating with capacity and load-balance
aux loss). TPU-first: expert weights live on the expert submesh and XLA
inserts the all-to-alls from shardings — no hand-written autograd
collective is needed.

Four dispatch implementations share one routing core (``_routing``):

- ``"gather"`` (default, the fast path): a slot->token index map built
  from tiny int32 scatters turns dispatch into a pure gather of the
  token matrix and combine into a gather of the expert outputs. Data
  movement is O(T*D); the only O(T*E) work is the router's position
  bookkeeping. This replaces the reference's fastmoe/CUDA delegation
  (``moe_layer.py:511``) — on TPU the win comes from NOT materializing
  capacity-shaped dense compute, not from a custom kernel.
- ``"einsum"`` (the reference check): one-hot [T,E,C] dispatch/combine
  einsums, numerically transparent and GSPMD-friendly, but the einsums
  cost T*E*C*D = capacity_factor*T^2*D FLOPs — quadratic in tokens, so
  dispatch dominates expert FLOPs at practical T. Kept as the oracle
  the fast paths are tested against (``tests/test_ops.py``).
- ``"grouped"`` (DROPLESS, per-shard): the Pallas grouped-matmul kernel
  (``ops.grouped_matmul``) — megablocks-style. No capacity and no
  dropped tokens: rows sort by expert, groups pad to the row-tile, and
  the expert FFN runs as grouped GEMMs with the per-tile expert index
  on scalar prefetch. The data-parallel-experts hot path; the kernel is
  opaque to GSPMD, so EP submesh sharding of its operands would force
  replication.
- ``"grouped_ep"`` (DROPLESS, expert-parallel): a ``shard_map`` over the
  expert submesh wrapping the same grouped kernel with EXPLICIT
  collectives — the TPU rendering of the reference's ``_AllToAll``
  expert process groups (``moe_layer.py:87``). Each shard routes its
  local tokens, exchanges per-(shard, expert) COUNTS with a tiny
  ``all_to_all`` so row padding stays tile-aligned and static-shaped,
  exchanges the token rows themselves with a second ``all_to_all``,
  runs the dropless grouped GEMMs on its local experts, and returns
  outputs through the reverse ``all_to_all`` and local combine. MoE
  FLOPs stay linear in tokens even with experts on different chips;
  the price is two all-to-alls each way, which ``parallel.planner``
  estimates against the capacity paths' quadratic dispatch.

Planner guidance (``parallel/planner.py`` prices all four): "grouped" on
a per-shard (no-EP) mesh; "grouped_ep" when experts shard across chips
and per-chip token counts are large (all-to-all comm is linear in T
where the capacity fallback's dispatch is quadratic); "gather" for
small-token EP configs; "einsum" only as the testing oracle.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# metric keys surfaced to callers of ``moe_ffn``; _routing may carry
# additional internal entries (per-expert routing fractions the EP path
# pmean-reduces to reproduce the GLOBAL aux loss exactly)
PUBLIC_METRICS = ("dropped_frac", "expert_load")


@dataclass
class MoEConfig:
    num_experts: int
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    top_k: int = 1  # 1 = switch routing, 2 = gshard-style
    aux_loss_weight: float = 0.01
    router_jitter: float = 0.0  # multiplicative logit noise during training
    # "gather" (fast, capacity-based) | "einsum" (reference oracle) |
    # "grouped" (DROPLESS Pallas grouped matmul — per-shard experts) |
    # "grouped_ep" (DROPLESS + expert-parallel: shard_map + all_to_all
    # around the grouped kernel — experts sharded over ``ep_axes``)
    dispatch: str = "gather"
    # grouped-dispatch kernel mode: None = auto (interpreter off TPU),
    # False forces Mosaic (the deviceless-AOT contract)
    kernel_interpret: Optional[bool] = None
    # "grouped_ep" only: mesh axis name(s) forming the expert submesh
    # (tokens shard their batch dim and expert weights their expert dim
    # over these axes). The default matches the canonical rule sets'
    # (data x fsdp) expert submesh (``sharding_rules.moe_rules``).
    ep_axes: Tuple[str, ...] = ("data", "fsdp")
    # "grouped_ep" only: explicit mesh; None = the AMBIENT mesh
    # (``jax.sharding.set_mesh``, what accelerate establishes while
    # tracing) — rebuilt by every accelerate, so elastic-safe.
    mesh: Any = None
    # "grouped_ep" only: split the [P, n, D] row exchange into this many
    # static chunks driven by a ppermute ring (``ops.ring``), with the
    # grouped GEMM on already-arrived chunks overlapping the in-flight
    # exchange (double-buffered). 1 = the one-shot ``all_to_all``
    # (serial exchange -> GEMM -> exchange). 0 = resolve the global
    # Context knob (``dispatch_chunks``) at TRACE time, which is what
    # lets ``ElasticTrainer.retune`` re-chunk a running job through the
    # program cache with zero recompiles on a prewarmed value.
    dispatch_chunks: int = 0
    # "grouped_ep" only: the WIRE precision of the row exchanges
    # (``ops.quantize``). "bf16" = the exchange carries the compute
    # dtype unchanged; "fp8" = rows quantize to block-scaled e4m3
    # (values + f32 per-block scales, both exchanged — ~0.56x the
    # bytes) BEFORE the all_to_all / ppermute ring, forward rows AND
    # backward cotangents, with the up-projection consuming the wire
    # rows through the dequant-in-kernel grouped matmul; "fp8_qdq" =
    # the reference oracle (quantize->dequantize locally, wire at full
    # precision — bitwise identical outputs to "fp8", used by tests
    # and for isolating transport from numerics). "" = resolve the
    # Context knob (``moe_precision``) at TRACE time — the same
    # retune-without-rebuild contract as ``dispatch_chunks``. Falls
    # back to "bf16" (logged) when the backend fails the fp8
    # capability probe (``shard_compat.fp8_wire_supported``).
    precision: str = ""


def _capacity(num_tokens: int, num_experts: int, factor: float,
              top_k: int = 1) -> int:
    """Per-expert queue length, gshard convention: capacity scales with
    top_k (k assignments per token means k*T total demand — a k=2
    config at factor 1.25 would otherwise drop >= 37.5% of assignments
    by construction, under perfectly uniform routing)."""
    return max(1, int(math.ceil(
        num_tokens * top_k * factor / num_experts
    )))


def _routing(
    logits: jax.Array,  # [T, E]
    capacity: int,
    top_k: int,
    rng: Optional[jax.Array],
    jitter: float,
) -> Tuple[List[Tuple[jax.Array, ...]], jax.Array, Dict[str, jax.Array]]:
    """Shared routing core: per-round (expert, position, keep, gate).

    Round-by-round filling (all k=0 choices claim queue positions
    before any k=1 choice) with arrival-order priority inside a round —
    the switch/gshard semantics both dispatch paths must agree on.
    Everything here is [T] or [T, E]; the capacity axis never
    materializes. Returns (rounds, aux_loss, metrics) where each round
    is (expert_idx [T]i32, pos [T]i32, keep [T]f32, gate [T]f32) and
    metrics carries the load-balance observability signals
    (``switch_gating.py:24-195`` parity: capacity-overflow accounting).
    """
    t, e = logits.shape
    if rng is not None and jitter > 0.0:
        noise = jax.random.uniform(
            rng, logits.shape, minval=1.0 - jitter, maxval=1.0 + jitter
        )
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    remaining = probs
    expert_fill = jnp.zeros((e,), jnp.int32)
    total_onehot = jnp.zeros((t, e), jnp.float32)
    kept_per_expert = jnp.zeros((e,), jnp.float32)
    rounds = []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, E]
        # position of each token within its expert's queue (arrival order)
        pos_in_expert = (
            jnp.cumsum(onehot, axis=0) - onehot
        ) * onehot  # [T, E]
        pos_in_expert = pos_in_expert + expert_fill[None, :] * onehot
        within = (pos_in_expert < capacity).astype(jnp.float32) * onehot
        pos = pos_in_expert.sum(axis=-1).astype(jnp.int32)  # [T]
        keep = within.sum(axis=-1)  # [T] 1.0 = assigned a queue slot
        gate = (probs * onehot).sum(axis=-1)  # [T]
        rounds.append((idx, pos, keep, gate))
        expert_fill = expert_fill + within.sum(axis=0).astype(jnp.int32)
        kept_per_expert = kept_per_expert + within.sum(axis=0)
        total_onehot = total_onehot + onehot
        remaining = remaining * (1.0 - onehot)

    # load-balance auxiliary loss (switch transformer eq. 4)
    frac_tokens = total_onehot.mean(axis=0)  # [E]
    frac_probs = probs.mean(axis=0)  # [E]
    aux_loss = e * jnp.sum(frac_tokens * frac_probs) / max(1, top_k)
    routed = total_onehot.sum(axis=0)  # [E] pre-drop demand per expert
    metrics = {
        # fraction of (token, round) assignments that overflowed capacity
        "dropped_frac": 1.0 - kept_per_expert.sum() / float(t * top_k),
        # pre-drop routing demand per expert, as a fraction of tokens;
        # uniform = 1/E. This is the signal the aux loss regularizes.
        "expert_load": routed / float(t * top_k),
        # internal (not in PUBLIC_METRICS): the aux loss's two per-expert
        # fraction vectors. The expert-parallel path pmean-reduces these
        # across token shards — means of equal-sized local means ARE the
        # global means, so the reduced aux equals the single-shard oracle
        "frac_tokens": frac_tokens,
        "frac_probs": frac_probs,
    }
    return rounds, aux_loss, metrics


def router_dispatch(
    logits: jax.Array,  # [T, E]
    capacity: int,
    top_k: int = 1,
    rng: Optional[jax.Array] = None,
    jitter: float = 0.0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compute (dispatch_mask [T,E,C], combine_weights [T,E,C], aux_loss).

    The reference-path materialization of ``_routing``: each token goes
    to its top-k experts, subject to a per-expert capacity; overflowing
    tokens are dropped (their combine weight is zero, so the residual
    path carries them).
    """
    t, e = logits.shape
    rounds, aux_loss, _ = _routing(logits, capacity, top_k, rng, jitter)
    dispatch, combine = _materialize(rounds, t, e, capacity)
    return dispatch, combine, aux_loss


def _materialize(rounds, t: int, e: int, capacity: int):
    """[T,E,C] one-hot dispatch/combine from routing rounds — the single
    source both ``router_dispatch`` and the einsum oracle build on."""
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    for idx, pos, keep, gate in rounds:
        within = jax.nn.one_hot(idx, e, dtype=jnp.float32) * keep[:, None]
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        dispatch = dispatch + within[:, :, None] * pos_oh[:, None, :]
        combine = combine + (
            gate[:, None, None] * within[:, :, None] * pos_oh[:, None, :]
        )
    return dispatch, combine


def _moe_compute_einsum(params, xt, rounds, capacity, e, activation):
    """[T,E,C] one-hot dispatch/combine (the reference check)."""
    t = xt.shape[0]
    dispatch, combine = _materialize(rounds, t, e, capacity)
    # all-to-all #1: tokens -> expert queues (XLA inserts the collective
    # when experts are mesh-sharded). The SPMD partitioner may log an
    # "involuntary full rematerialization" for the [T,1,1] gate broadcast
    # when dispatch/combine consumers want different T shardings — that
    # tensor is tokens*4 bytes, so the replicate-and-repartition it falls
    # back to is noise, not a bandwidth problem.
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch.astype(xt.dtype), xt
    )  # [E, C, D]
    h = activation(jnp.einsum(
        "ecd,edf->ecf", expert_in, params["experts"]["up"]["kernel"]
    ))
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["experts"]["down"]["kernel"]
    )  # [E, C, D]
    # all-to-all #2: expert queues -> tokens
    return jnp.einsum("tec,ecd->td", combine.astype(xt.dtype), expert_out)


def _moe_compute_gather(params, xt, rounds, capacity, e, activation):
    """Slot-indexed dispatch/combine (the fast path).

    A [E*C+1] int32 slot->token map is built with scatters whose
    operand is tokens*4 bytes (dropped tokens write the sentinel slot);
    the [E,C,D] expert input is then a single gather of the token
    matrix, and combine is a gather of the expert outputs weighted by
    the gates. Identical routing semantics to the einsum path by
    construction — both consume the same ``_routing`` rounds.
    """
    t, d = xt.shape
    n_slots = e * capacity
    token_ids = jnp.arange(t, dtype=jnp.int32)
    # sentinel slot n_slots absorbs dropped tokens; sentinel token t
    # backs empty slots with a zero row
    slot_token = jnp.full((n_slots + 1,), t, jnp.int32)
    for idx, pos, keep, _gate in rounds:
        flat = jnp.where(keep > 0, idx * capacity + pos, n_slots)
        slot_token = slot_token.at[flat].set(token_ids)
    x_pad = jnp.concatenate([xt, jnp.zeros((1, d), xt.dtype)], axis=0)
    expert_in = x_pad[slot_token[:n_slots]].reshape(e, capacity, d)
    h = activation(jnp.einsum(
        "ecd,edf->ecf", expert_in, params["experts"]["up"]["kernel"]
    ))
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["experts"]["down"]["kernel"]
    ).reshape(n_slots, d)
    out = jnp.zeros((t, d), xt.dtype)
    for idx, pos, keep, gate in rounds:
        flat = jnp.clip(idx * capacity + pos, 0, n_slots - 1)
        weight = (gate * keep).astype(xt.dtype)[:, None]
        out = out + expert_out[flat] * weight
    return out


def _moe_compute_grouped(params, xt, rounds, e, activation,
                         block_t: int = 128,
                         interpret: Optional[bool] = None):
    """DROPLESS dispatch via the grouped-matmul Pallas kernel
    (``ops.grouped_matmul``) — megablocks-style: NO capacity, NO
    dropped tokens.

    Every (token, round) assignment is served: rows are sorted by
    expert with each group padded up to the row-tile, and the expert
    FFN runs as two grouped matmuls whose per-tile expert index rides
    scalar prefetch. Static shapes throughout — padded rows are the
    upper bound ceil(T*k / bt)*bt + E*bt, so XLA sees one program
    regardless of the routing. Pad overhead is at most E*(block_t-1)
    rows vs the capacity approach's (factor-1)*T slots plus overflow
    drops.

    Scope: the per-shard (data-parallel experts) hot path. With experts
    sharded over an expert submesh (EP), use the "gather"/"einsum"
    dispatches — the kernel is opaque to GSPMD, so EP sharding of its
    operands would force replication instead of all-to-alls.
    """
    from dlrover_tpu.ops.grouped_matmul import grouped_matmul

    t, d = xt.shape
    k = len(rounds)
    n = t * k
    # assignments in round-major arrival order (matches _routing's
    # queue discipline: every k=0 choice precedes any k=1 choice)
    expert_a = jnp.concatenate([r[0] for r in rounds])  # [n] int32
    gate_a = jnp.concatenate([r[3] for r in rounds])  # [n] f32
    token_a = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    # with capacity == T nothing overflows, so _routing's queue
    # positions ARE each assignment's within-expert arrival rank
    # (cross-round fill included) — no second [n, E] cumsum needed
    rank = jnp.concatenate([r[1] for r in rounds])  # [n] int32
    counts = jnp.zeros((e,), jnp.int32).at[expert_a].add(1)  # [E]
    # every expert gets AT LEAST one tile, even with zero routed
    # tokens: its sentinel-zero rows make the dw kernel INITIALIZE that
    # expert's gradient block to zero — an unvisited output block would
    # be uninitialized garbage on real TPU (interpret mode zero-fills,
    # which would mask the bug)
    padded = jnp.maximum(
        ((counts + block_t - 1) // block_t), 1
    ) * block_t  # [E]
    ends = jnp.cumsum(padded).astype(jnp.int32)  # [E]
    offsets = ends - padded.astype(jnp.int32)  # [E] exclusive
    row = offsets[expert_a] + rank  # [n] destination row, unique
    # static padded-row bound: every group full + its tile padding
    tp = ((n + block_t - 1) // block_t) * block_t + e * block_t
    # row -> token map; pad rows read the zero sentinel row of x_pad
    row_token = jnp.full((tp,), t, jnp.int32).at[row].set(token_a)
    x_pad = jnp.concatenate([xt, jnp.zeros((1, d), xt.dtype)], axis=0)
    x_sorted = x_pad[row_token]
    # tile i belongs to the expert whose [offset, end) span covers it;
    # tiles past the last real group clip to the final expert (their
    # rows are all sentinel zeros — garbage compute, masked by unsort)
    tile_start = jnp.arange(tp // block_t, dtype=jnp.int32) * block_t
    tile_expert = jnp.clip(
        jnp.searchsorted(ends, tile_start, side="right"), 0, e - 1
    ).astype(jnp.int32)

    h = activation(grouped_matmul(
        x_sorted, params["experts"]["up"]["kernel"], tile_expert,
        block_t, 512, interpret,
    ))
    y_sorted = grouped_matmul(
        h, params["experts"]["down"]["kernel"], tile_expert,
        block_t, 512, interpret,
    )
    # combine: unsort + gate weight, summing each token's k rounds
    y_a = y_sorted[row] * gate_a[:, None].astype(y_sorted.dtype)
    return jnp.zeros((t, d), xt.dtype).at[token_a].add(
        y_a.astype(xt.dtype)
    )


def ambient_ep_mesh(axes: Tuple[str, ...]):
    """The ambient mesh (``shard_compat.ambient_mesh`` — what
    ``accelerate`` establishes while tracing, on either jax era) when it
    carries every axis in ``axes`` with none of them already manual;
    else None.

    Mirrors ``ops.ring_attention.ambient_ring_mesh``: a mesh frozen into
    a config at startup would survive ``on_world_change``'s
    re-accelerate and make the shard_map reference departed devices; the
    ambient mesh is rebuilt with each accelerate, so ``dispatch=
    "grouped_ep"`` stays elastic-safe with ``mesh=None``.
    """
    from dlrover_tpu.ops.shard_compat import ambient_mesh_with_axes

    return ambient_mesh_with_axes(axes)


def _resolve_ep_mesh(config: "MoEConfig"):
    """(mesh, axes, ep_degree) for ``dispatch="grouped_ep"``.

    ``(None, axes, 1)`` when no usable expert submesh exists — the
    caller degrades to the per-shard "grouped" path (identical math;
    the elastic world may legitimately have shrunk the submesh to 1).
    """
    axes = tuple(config.ep_axes)
    mesh = config.mesh
    if mesh is None:
        mesh = ambient_ep_mesh(axes)
        if mesh is None:
            return None, axes, 1
    sizes = dict(zip(mesh.axis_names, mesh.axis_sizes))
    missing = [a for a in axes if a not in sizes]
    if missing:
        raise ValueError(
            f"grouped_ep: mesh {tuple(mesh.axis_names)} lacks expert "
            f"submesh axes {missing}"
        )
    ep = math.prod(sizes[a] for a in axes)
    return (mesh, axes, ep) if ep > 1 else (None, axes, 1)


def resolve_dispatch_chunks(config: "MoEConfig") -> int:
    """The effective ``dispatch_chunks`` for a config: an explicit
    positive value wins; 0 resolves the global Context knob at TRACE
    time (``Context.dispatch_chunks``), which is how the runtime
    optimizer's chosen chunking reaches a re-traced program without
    rebuilding the model config."""
    c = int(getattr(config, "dispatch_chunks", 0) or 0)
    if c > 0:
        return c
    from dlrover_tpu.common.config import get_context

    return max(1, int(getattr(get_context(), "dispatch_chunks", 1)))


def resolve_moe_precision(config: "MoEConfig") -> str:
    """The effective wire precision for a config at TRACE time: an
    explicit ``config.precision`` wins; "" resolves the global Context
    knob (``moe_precision``) — which is how the runtime optimizer's
    chosen precision reaches a re-traced program without rebuilding the
    model config (the ``dispatch_chunks`` pattern). A quantized choice
    degrades to "bf16" (logged) when the backend fails the fp8
    capability probe."""
    p = (getattr(config, "precision", "") or "").strip()
    if not p:
        from dlrover_tpu.common.config import get_context

        p = str(getattr(get_context(), "moe_precision", "bf16") or
                "bf16").strip()
    from dlrover_tpu.ops.quantize import PRECISIONS

    if p not in PRECISIONS:
        raise ValueError(
            f"unknown MoE precision {p!r}; choose one of {PRECISIONS}"
        )
    if p != "bf16":
        from dlrover_tpu.ops.shard_compat import fp8_wire_supported

        if not fp8_wire_supported():
            from dlrover_tpu.common.log import get_logger

            get_logger("ops.moe").warning(
                "moe precision %r requested but the backend fails the "
                "fp8 capability probe; running the bf16 wire", p,
            )
            return "bf16"
    return p


def _regroup_window(recv, lo, nc, up_l, down_l, *, x_chunk=None,
                    v_chunk=None, s_chunk=None, ep: int, el: int,
                    block_t: int, interpret, activation, out_dtype):
    """Received block rows [lo, lo+nc) from every source -> expert
    outputs in the same layout (invalid slots zero).

    All index math comes from the exchanged counts (``recv`` [P, el]),
    so every shape is static; at lo=0, nc=n this IS the unchunked
    regroup (chunk-window clips are no-ops). The rows arrive either at
    full precision (``x_chunk`` [P, nc, D]) or at wire precision
    (``v_chunk`` [P, nc, D] e4m3 + ``s_chunk`` [P, nc, D/B] f32 scales,
    the ``ops.quantize`` layout) — the quantized form feeds the
    up-projection through the dequant-in-kernel grouped matmul, bitwise
    equal to dequantizing first (the exchange buffer is never
    re-materialized at full width just to enter the GEMM). Module-level
    (no closures) so the quantized dispatch's custom_vjp boundary can
    call it with everything explicit.
    """
    from dlrover_tpu.ops.grouped_matmul import (
        grouped_matmul,
        grouped_matmul_quantized,
    )

    quantized = v_chunk is not None
    rows = v_chunk if quantized else x_chunk
    d = rows.shape[-1]
    csum = jnp.cumsum(recv, axis=1)  # [P, el]
    tot = csum[:, -1]  # [P] real rows per source block
    group_start = csum - recv  # [P, el] within-block group starts

    r_idx = lo + jnp.arange(nc, dtype=jnp.int32)
    le_r = jax.vmap(
        lambda c, r: jnp.searchsorted(c, r, side="right")
    )(csum, jnp.broadcast_to(r_idx, (ep, nc)))  # [P, nc]
    valid = r_idx[None, :] < tot[:, None]  # [P, nc]
    le_r = jnp.clip(le_r, 0, el - 1).astype(jnp.int32)
    src_rows = jnp.arange(ep, dtype=jnp.int32)[:, None]
    # rows of each (source, local-expert) group that fall in this
    # chunk's window, and the group's start within it
    cnt = jnp.clip(
        jnp.minimum(csum, lo + nc)
        - jnp.maximum(group_start, lo), 0, nc
    )  # [P, el]
    start = jnp.maximum(group_start[src_rows, le_r], lo)
    pre = jnp.cumsum(cnt, axis=0) - cnt  # earlier sources
    rank_r = pre[src_rows, le_r] + (r_idx[None, :] - start)
    m_le = cnt.sum(axis=0)  # [el] chunk rows per local expert
    padded = jnp.maximum(
        (m_le + block_t - 1) // block_t, 1
    ) * block_t
    ends = jnp.cumsum(padded).astype(jnp.int32)
    offs = (ends - padded).astype(jnp.int32)
    # static bound: every group full + its tile padding (and every
    # zero-row expert still owns one sentinel tile — dw init, see
    # grouped_matmul)
    tp = (
        ((ep * nc + block_t - 1) // block_t) * block_t
        + el * block_t
    )
    dest_row = jnp.where(valid, offs[le_r] + rank_r, tp)
    q_flat = jnp.arange(ep * nc, dtype=jnp.int32)
    row_src = jnp.full((tp + 1,), ep * nc, jnp.int32).at[
        dest_row.reshape(-1)
    ].set(q_flat)[:tp]
    tile_start = jnp.arange(
        tp // block_t, dtype=jnp.int32
    ) * block_t
    tile_expert = jnp.clip(
        jnp.searchsorted(ends, tile_start, side="right"),
        0, el - 1,
    ).astype(jnp.int32)
    if quantized:
        # gather values AND scales by the same row map; pad rows read
        # zero sentinel rows on both sides (zero values decode to zero
        # under any scale)
        nb = s_chunk.shape[-1]
        v_pad = jnp.concatenate(
            [v_chunk.reshape(ep * nc, d),
             jnp.zeros((1, d), v_chunk.dtype)], axis=0
        )
        s_pad = jnp.concatenate(
            [s_chunk.reshape(ep * nc, nb),
             jnp.zeros((1, nb), s_chunk.dtype)], axis=0
        )
        h = activation(grouped_matmul_quantized(
            v_pad[row_src], s_pad[row_src], up_l, tile_expert,
            block_t, 512, interpret, jnp.float32,
        ))
    else:
        x_pad_c = jnp.concatenate(
            [x_chunk.reshape(ep * nc, d),
             jnp.zeros((1, d), x_chunk.dtype)], axis=0
        )
        h = activation(grouped_matmul(
            x_pad_c[row_src], up_l, tile_expert, block_t, 512,
            interpret,
        ))
    y_sorted = grouped_matmul(
        h, down_l, tile_expert, block_t, 512, interpret,
    )
    # back to the chunk's recv layout (invalid slots zero)
    y_flat = y_sorted[
        jnp.clip(dest_row, 0, tp - 1).reshape(-1)
    ]
    y_flat = jnp.where(
        valid.reshape(-1)[:, None], y_flat, 0
    ).astype(out_dtype)
    return y_flat.reshape(ep, nc, d)


def _quantized_dispatch_fwd_impl(x_send3, up_l, down_l, recv,
                                 axes, ep, el, chunks, block_t,
                                 interpret, precision, activation):
    """Forward of the quantized row dispatch: quantize -> exchange ->
    grouped GEMMs -> quantize -> reverse exchange -> dequantize.

    Returns (y_ret, (v_recv, s_recv)) — the received wire rows are the
    backward residual (at 1.125 bytes/element they are the CHEAPEST
    exact record of what the GEMMs consumed).

    "fp8" exchanges the (values, scales) pair — the wire carries ~0.56x
    the bf16 bytes; "fp8_qdq" applies the identical quantize->
    dequantize at the SOURCE of every exchange and wires full precision
    — bitwise the same result, because quantization is per-row and the
    exchange is a pure row permutation (the commuting square the exact
    tests pin). Chunked (C > 1) keeps PR 10's double-buffered ring
    schedule: chunk c+1's value+scale rings are issued before chunk c's
    GEMMs."""
    from dlrover_tpu.ops.quantize import (
        dequantize_block_scaled,
        quantize_block_scaled,
    )

    n = x_send3.shape[1]
    wire_fp8 = precision == "fp8"
    v, s = quantize_block_scaled(x_send3)

    def exch(a):
        return lax.all_to_all(a, axes, 0, 0)

    def gemms(vc, sc, xc, lo, nc):
        return _regroup_window(
            recv, lo, nc, up_l, down_l,
            x_chunk=xc, v_chunk=vc, s_chunk=sc,
            ep=ep, el=el, block_t=block_t, interpret=interpret,
            activation=activation, out_dtype=jnp.float32,
        )

    # the backward residual is the received wire rows: (values, scales)
    # for the fp8 wire, the received dequantized rows themselves for
    # the qdq reference — bitwise the same dequant-space array (the
    # exchange commutes with the per-row decode), and the form each
    # mode already holds. Re-encoding the reference's received rows
    # would NOT be bitwise (448 is not a power of two, so
    # quantize(dequantize(q, s)) reproduces neither q nor s exactly).
    if chunks <= 1:
        if wire_fp8:
            vr, sr = exch(v), exch(s)
            y = gemms(vr, sr, None, 0, n)
            residual = (vr, sr)
        else:
            xr = exch(dequantize_block_scaled(v, s))
            y = gemms(None, None, xr, 0, n)
            residual = (xr, jnp.zeros((0,), jnp.float32))
        wv, ws = quantize_block_scaled(y)
        if wire_fp8:
            y_ret = dequantize_block_scaled(exch(wv), exch(ws))
        else:
            y_ret = exch(dequantize_block_scaled(wv, ws))
        return y_ret, residual

    from dlrover_tpu.ops.ring import ring_all_to_all

    def ring(a):
        return ring_all_to_all(a, axes, ep)

    nc = n // chunks

    def wire_in(c):
        """Issue chunk c's exchange (the double-buffered prefetch)."""
        lo, hi = c * nc, (c + 1) * nc
        if wire_fp8:
            return (ring(v[:, lo:hi]), ring(s[:, lo:hi]))
        xq = dequantize_block_scaled(v[:, lo:hi], s[:, lo:hi])
        return (ring(xq),)

    cur = wire_in(0)
    parts, res_a, res_b = [], [], []
    for c in range(chunks):
        nxt = wire_in(c + 1) if c + 1 < chunks else None
        if wire_fp8:
            vr_c, sr_c = cur
            y_c = gemms(vr_c, sr_c, None, c * nc, nc)
            res_a.append(vr_c)
            res_b.append(sr_c)
        else:
            (xr_c,) = cur
            y_c = gemms(None, None, xr_c, c * nc, nc)
            res_a.append(xr_c)
        wv, ws = quantize_block_scaled(y_c)
        if wire_fp8:
            parts.append((ring(wv), ring(ws)))
        else:
            parts.append(dequantize_block_scaled(wv, ws))
        cur = nxt
    if wire_fp8:
        y_ret = jnp.concatenate(
            [dequantize_block_scaled(pv, ps) for pv, ps in parts],
            axis=1,
        )
        residual = (jnp.concatenate(res_a, axis=1),
                    jnp.concatenate(res_b, axis=1))
    else:
        y_ret = jnp.concatenate([ring(p) for p in parts], axis=1)
        residual = (jnp.concatenate(res_a, axis=1),
                    jnp.zeros((0,), jnp.float32))
    return y_ret, residual


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9,
                                                    10, 11))
def _quantized_dispatch(x_send3, up_l, down_l, recv,
                        axes, ep, el, chunks, block_t, interpret,
                        precision, activation):
    """The quantized row dispatch, differentiable end to end: the wire
    carries block-scaled fp8 in BOTH directions (forward rows and
    backward cotangents — that is what halves the all-to-all bytes the
    G106 audit counts, not just the forward leg).

    Autodiff cannot run through an fp8 primal (the cotangent of an e4m3
    array is e4m3 — gradients would be destroyed at 2 decimal digits),
    so the boundary is a custom VJP: the backward re-derives the GEMM
    gradients by ``jax.vjp`` over the DEQUANT-SPACE compute on the
    saved wire rows (a remat-style forward replay — the fp8 residual is
    8x smaller than saving ``h``), and wires each cotangent exchange
    through the same quantize -> exchange -> dequantize transform as
    the forward (straight-through at the quantize step). The reference
    oracle ("fp8_qdq") shares this exact code path with the wire left
    at full precision, which is why the equality the tests pin is
    bitwise and not approximate."""
    y, _res = _quantized_dispatch_fwd_impl(
        x_send3, up_l, down_l, recv, axes, ep, el, chunks, block_t,
        interpret, precision, activation,
    )
    return y


def _qd_fwd(x_send3, up_l, down_l, recv, axes, ep, el, chunks, block_t,
            interpret, precision, activation):
    y, (res_a, res_b) = _quantized_dispatch_fwd_impl(
        x_send3, up_l, down_l, recv, axes, ep, el, chunks, block_t,
        interpret, precision, activation,
    )
    # the empty array exists only to carry x_send3's dtype into the
    # backward (a bare numpy dtype is not a valid residual leaf)
    return y, (res_a, res_b, up_l, down_l, recv,
               jnp.zeros((0,), x_send3.dtype))


def _qd_bwd(axes, ep, el, chunks, block_t, interpret, precision,
            activation, res, g):
    from dlrover_tpu.ops.quantize import (
        dequantize_block_scaled,
        quantize_block_scaled,
    )

    res_a, res_b, up_l, down_l, recv, x_proto = res
    x_dtype = x_proto.dtype
    n = g.shape[1]
    wire_fp8 = precision == "fp8"
    if chunks > 1:
        from dlrover_tpu.ops.ring import ring_all_to_all

        def exch(a):
            # same wire as the forward (ring: the diagonal block stays
            # off the wire); chunk windows act per row, so one
            # full-array ring is bitwise the per-chunk concatenation
            return ring_all_to_all(a, axes, ep)
    else:
        def exch(a):
            return lax.all_to_all(a, axes, 0, 0)

    def wire(a):
        """One backward cotangent exchange: quantized at the source
        exactly like the forward rows (or qdq'd locally with a
        full-precision wire in the reference mode)."""
        gv, gs = quantize_block_scaled(a)
        if wire_fp8:
            return dequantize_block_scaled(exch(gv), exch(gs))
        return exch(dequantize_block_scaled(gv, gs))

    # the return exchange's backward: send layout -> recv layout (the
    # exchange operator is an involution, so the same op routes it)
    g_y = wire(g.astype(jnp.float32))

    def inner(xd, up, down):
        # the dequant-space compute the forward is bitwise equal to;
        # mirrored per chunk window so the vjp sees the same GEMM
        # partitioning
        if chunks <= 1:
            return _regroup_window(
                recv, 0, n, up, down, x_chunk=xd,
                ep=ep, el=el, block_t=block_t, interpret=interpret,
                activation=activation, out_dtype=jnp.float32,
            )
        nc = n // chunks
        return jnp.concatenate([
            _regroup_window(
                recv, c * nc, nc, up, down,
                x_chunk=xd[:, c * nc:(c + 1) * nc],
                ep=ep, el=el, block_t=block_t, interpret=interpret,
                activation=activation, out_dtype=jnp.float32,
            ) for c in range(chunks)
        ], axis=1)

    # the dequant-space input the forward consumed: decode the fp8
    # residual, or the qdq reference's received rows as-is (bitwise the
    # same array — the commuting square again)
    x_deq = (dequantize_block_scaled(res_a, res_b) if wire_fp8
             else res_a)
    _y_replay, vjp_fn = jax.vjp(inner, x_deq, up_l, down_l)
    gx_deq, dup, ddown = vjp_fn(g_y)
    # the row exchange's backward: recv layout -> send layout
    gx = wire(gx_deq).astype(x_dtype)
    return gx, dup, ddown, None


_quantized_dispatch.defvjp(_qd_fwd, _qd_bwd)


def _moe_compute_grouped_ep(params, xt, config: "MoEConfig", activation,
                            mesh, axes: Tuple[str, ...], ep: int,
                            rng, jitter: float,
                            block_t: int = 128,
                            chunks: int = 1,
                            precision: str = "bf16"):
    """DROPLESS dispatch with experts SHARDED over the ``axes`` submesh:
    shard_map + two ``lax.all_to_all`` exchanges around the grouped
    Pallas kernel — megablocks-style droplessness with MoE FLOPs linear
    in tokens even when experts live on different chips.

    Per shard (P = ep shards, el = E/P local experts, Tl local tokens,
    n = Tl * top_k local assignments):

      1. route the LOCAL tokens over all E experts (router replicated);
         aux-loss fractions pmean across shards so the loss equals the
         single-shard oracle exactly;
      2. exchange per-(dest shard, local expert) COUNTS with a tiny
         int32 all_to_all — the receiver can then compute every row's
         tile-aligned destination locally, so all row buffers keep
         STATIC shapes (zero recompiles across steps);
      3. exchange token rows with a [P, n, D] all_to_all (block s =
         rows destined to shard s, grouped by that shard's local
         experts in local arrival order). n is the static worst case —
         all local assignments to one shard — which is what droplessness
         without dynamic shapes costs; the planner prices exactly these
         bytes (``planner`` "moe_disp_comm_s");
      4. regroup received rows by local expert, pad each group to the
         row tile, run the two grouped GEMMs (the per-shard kernel,
         unchanged — every local expert owns >= 1 tile so dw blocks
         initialize, see ``grouped_matmul``);
      5. reverse all_to_all and combine locally (unsort + gate, summing
         each token's top_k rounds).

    ``chunks`` > 1 (the comm/compute-overlap mode): the [P, n, D] row
    exchange of steps 3/5 is split into C static chunks of n/C rows
    per block, each exchanged by a ppermute ring (``ops.ring``) instead
    of the opaque one-shot ``all_to_all``, DOUBLE-BUFFERED — chunk
    c+1's exchange is issued before chunk c's grouped GEMMs, and chunk
    c's reverse exchange before chunk c+1's GEMMs, so XLA's
    latency-hiding scheduler can run the in-flight exchange under the
    compute on already-arrived rows. Per-row math is unchanged (each
    row's output is x_row @ W of its expert, independent of chunking),
    so C is a pure schedule knob: outputs are exactly the C=1 path's,
    total wire bytes stay the all_to_all's (minus the diagonal block
    that never needed the wire — the G106 audit's parity contract),
    shapes stay static per C, and droplessness is untouched. n % C != 0
    degrades to C=1 at trace time (logged).

    Differentiable end to end: the collectives transpose to their
    reverses and the kernel brings its custom VJP, so the backward runs
    the same exchanges (all-to-alls, or the mirrored ppermute ring) in
    the opposite direction.

    Returns (out [T, D], aux_loss, metrics) — metrics are the pmean'd
    global load-balance signals, ``dropped_frac`` identically 0.
    """
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.ops.shard_compat import (
        get_shard_map,
        shard_map_check_kwargs,
    )

    shard_map = get_shard_map()

    t, d = xt.shape
    e = config.num_experts
    top_k = config.top_k
    if e % ep:
        raise ValueError(
            f"grouped_ep: num_experts={e} not divisible by the expert "
            f"submesh of {ep} shards ({axes})"
        )
    if t % ep:
        raise ValueError(
            f"grouped_ep: {t} tokens not divisible by the expert "
            f"submesh of {ep} shards ({axes})"
        )
    el = e // ep
    interpret = config.kernel_interpret
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    # chunk validation happens at TRACE time (shapes are static): an
    # indivisible row count degrades to the one-shot exchange rather
    # than changing the row layout
    chunks = max(1, int(chunks))
    n_static = (t // ep) * config.top_k
    if chunks > 1 and (n_static % chunks or chunks > n_static):
        from dlrover_tpu.common.log import get_logger

        get_logger("ops.moe").warning(
            "grouped_ep: dispatch_chunks=%d does not divide the %d "
            "local assignment rows; running unchunked (C=1)",
            chunks, n_static,
        )
        chunks = 1

    def body(xt_l, router_k, up_l, down_l, rng_l):
        tl = xt_l.shape[0]
        shard = lax.axis_index(axes)
        # decorrelate router jitter across token shards
        rng_s = jax.random.fold_in(rng_l, shard)
        logits = xt_l @ router_k  # [Tl, E]
        # capacity = Tl: dropless — nothing can overflow, and the
        # round positions ARE per-expert local arrival ranks
        rounds, _, metrics_l = _routing(logits, tl, top_k, rng_s, jitter)

        k = len(rounds)
        n = tl * k
        expert_a = jnp.concatenate([r[0] for r in rounds])  # [n] i32
        gate_a = jnp.concatenate([r[3] for r in rounds])  # [n] f32
        rank_a = jnp.concatenate([r[1] for r in rounds])  # [n] i32
        token_a = jnp.tile(jnp.arange(tl, dtype=jnp.int32), k)
        # contiguous expert ownership: expert g lives on shard g // el
        # as local expert g % el — exactly how PartitionSpec shards the
        # leading [E] dim over the (row-major) combined axis index
        dest = expert_a // el  # [n] owner shard
        le_a = expert_a % el  # [n] owner's local expert
        counts = jnp.zeros((ep, el), jnp.int32).at[dest, le_a].add(1)
        # send layout: per-dest block of n rows; within a block, rows
        # group by the dest's local expert in local arrival order
        block_off = jnp.cumsum(counts, axis=1) - counts  # [P, el]
        send_pos = dest * n + block_off[dest, le_a] + rank_a  # unique
        send_token = jnp.full((ep * n,), tl, jnp.int32).at[send_pos].set(
            token_a
        )
        x_pad = jnp.concatenate(
            [xt_l, jnp.zeros((1, d), xt_l.dtype)], axis=0
        )
        x_send = x_pad[send_token]  # [P*n, D]; pad rows = zero sentinel

        # all-to-all #1 (tiny): counts — recv[s, le] = rows shard s is
        # sending for my local expert le. Never quantized: the regroup
        # index math must be exact, and [P, el] int32 is wire noise.
        recv = lax.all_to_all(counts, axes, 0, 0)  # [P, el]

        def regroup_gemm(x_chunk, lo, nc):
            return _regroup_window(
                recv, lo, nc, up_l, down_l, x_chunk=x_chunk,
                ep=ep, el=el, block_t=block_t, interpret=interpret,
                activation=activation, out_dtype=xt_l.dtype,
            )

        x_send3 = x_send.reshape(ep, n, d)
        if precision != "bf16":
            # the LOW-PRECISION wire: rows quantize to block-scaled
            # e4m3 BEFORE the exchange (values + f32 scales both ride
            # the wire — ~0.56x the bf16 bytes the planner prices and
            # G106 audits), the up-projection consumes them through
            # the dequant-in-kernel grouped matmul, and the backward
            # cotangent exchanges quantize the same way through the
            # custom VJP boundary. "fp8_qdq" is the bitwise reference
            # with the wire left at full precision.
            y_ret = _quantized_dispatch(
                x_send3, up_l, down_l, recv,
                axes, ep, el, chunks, block_t, interpret,
                precision, activation,
            ).astype(xt_l.dtype)
        elif chunks <= 1:
            # all-to-all #2: the token rows, one shot (serial)
            x_recv = lax.all_to_all(x_send3, axes, 0, 0)
            y_ret = lax.all_to_all(
                regroup_gemm(x_recv, 0, n), axes, 0, 0
            )  # [P, n, D]
        else:
            # chunked double-buffered exchange: chunk c+1's ring
            # permutes (and chunk c's reverse ring) carry no data
            # dependency on chunk c's GEMMs, so the scheduler can run
            # them under the compute — the overlap the one-shot
            # all_to_all structurally forbids
            from dlrover_tpu.ops.ring import ring_all_to_all

            nc = n // chunks
            cur = ring_all_to_all(x_send3[:, :nc], axes, ep)
            parts = []
            for c in range(chunks):
                nxt = (
                    ring_all_to_all(
                        x_send3[:, (c + 1) * nc:(c + 2) * nc],
                        axes, ep,
                    ) if c + 1 < chunks else None
                )
                y_c = regroup_gemm(cur, c * nc, nc)
                parts.append(ring_all_to_all(y_c, axes, ep))
                cur = nxt
            y_ret = jnp.concatenate(parts, axis=1)  # [P, n, D]
        # combine: each assignment's result sits at its own send_pos
        y_a = y_ret.reshape(ep * n, d)[send_pos]  # [n, D]
        out_l = jnp.zeros((tl, d), xt_l.dtype).at[token_a].add(
            (y_a * gate_a[:, None].astype(y_a.dtype)).astype(xt_l.dtype)
        )

        # aux loss from GLOBAL routing fractions: pmean of equal-sized
        # local means == the global mean, so this equals the oracle
        ft = lax.pmean(metrics_l["frac_tokens"], axes)
        fp = lax.pmean(metrics_l["frac_probs"], axes)
        aux = e * jnp.sum(ft * fp) / max(1, top_k)
        load = lax.pmean(metrics_l["expert_load"], axes)
        return out_l, aux, load

    spec_tok = P(axes)  # dim 0 over the combined expert submesh
    spec_exp = P(axes)  # weights: expert dim over the same submesh
    rep = P()
    check_kw = shard_map_check_kwargs(shard_map)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec_tok, rep, spec_exp, spec_exp, rep),
        out_specs=(spec_tok, rep, rep),
        **check_kw,
    )
    out, aux, load = fn(
        xt, params["router"]["kernel"],
        params["experts"]["up"]["kernel"],
        params["experts"]["down"]["kernel"],
        rng,
    )
    metrics = {
        "dropped_frac": jnp.zeros((), jnp.float32),  # dropless
        "expert_load": load,
    }
    return out, aux.astype(jnp.float32), metrics


def moe_ffn(
    params: dict,
    x: jax.Array,  # [B, S, D]
    config: MoEConfig,
    activation: Callable = jax.nn.gelu,
    train: bool = True,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Switch-FFN block. params:
      router/kernel: [D, E]
      experts/up/kernel:   [E, D, F]
      experts/down/kernel: [E, F, D]
    Returns (output [B,S,D], aux_loss scalar, metrics dict) where
    metrics = {"dropped_frac" scalar, "expert_load" [E]} — the
    load-balance observability signals, computed by the router at
    negligible cost and surfaced as step metrics by the trainer.
    """
    dispatch = config.dispatch
    if dispatch not in ("gather", "einsum", "grouped", "grouped_ep"):
        raise ValueError(
            f"unknown MoE dispatch {config.dispatch!r}; choose "
            f"'gather' (fast, capacity), 'einsum' (reference oracle), "
            f"'grouped' (dropless Pallas kernel, per-shard experts) or "
            f"'grouped_ep' (dropless + expert-parallel all-to-all)"
        )
    b, s, d = x.shape
    t = b * s
    xt = x.reshape(t, d)
    jitter = config.router_jitter if train else 0.0
    if dispatch == "grouped_ep":
        mesh, axes, ep = _resolve_ep_mesh(config)
        if ep > 1:
            # routing happens INSIDE the shard_map (per local shard) so
            # the two all-to-alls move rows straight to owner experts
            out, aux, metrics = _moe_compute_grouped_ep(
                params, xt, config, activation, mesh, axes, ep,
                rng, jitter,
                chunks=resolve_dispatch_chunks(config),
                precision=resolve_moe_precision(config),
            )
            return out.reshape(b, s, d), aux, metrics
        # no usable expert submesh (single shard, elastic shrink, or no
        # mesh context): the per-shard dropless path is the same math
        dispatch = "grouped"
    logits = xt @ params["router"]["kernel"]  # [T, E]
    factor = config.capacity_factor if train else config.eval_capacity_factor
    if dispatch == "grouped":
        # DROPLESS: no capacity limit — every assignment is served, so
        # route with capacity = T (nothing can overflow) and the
        # metrics honestly report dropped_frac == 0
        capacity = t
    else:
        capacity = _capacity(t, config.num_experts, factor,
                             config.top_k)
    rounds, aux, metrics = _routing(
        logits, capacity, config.top_k, rng, jitter,
    )
    metrics = {k: metrics[k] for k in PUBLIC_METRICS}
    if dispatch == "grouped":
        out = _moe_compute_grouped(
            params, xt, rounds, config.num_experts, activation,
            interpret=config.kernel_interpret,
        )
    else:
        compute = (_moe_compute_einsum if dispatch == "einsum"
                   else _moe_compute_gather)
        out = compute(params, xt, rounds, capacity, config.num_experts,
                      activation)
    return out.reshape(b, s, d), aux.astype(jnp.float32), metrics


def init_moe_params(rng, d_model: int, d_ff: int, num_experts: int,
                    dtype=jnp.float32) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "router": {
            "kernel": jax.random.normal(k1, (d_model, num_experts),
                                        dtype) * scale_in,
        },
        "experts": {
            "up": {"kernel": jax.random.normal(
                k2, (num_experts, d_model, d_ff), dtype) * scale_in},
            "down": {"kernel": jax.random.normal(
                k3, (num_experts, d_ff, d_model), dtype) * scale_out},
        },
    }
