"""Mixture-of-Experts: router, capacity-based dispatch, expert parallelism.

Role parity: ``atorch/atorch/modules/moe/moe_layer.py:22-565`` (expert
process groups + ``_AllToAll`` autograd + ``Experts``) and
``switch_gating.py:24-195`` (top-1 gating with capacity and load-balance
aux loss). TPU-first: dispatch/combine are one-hot einsums over a
[tokens, experts, capacity] tensor; with expert weights sharded on the
expert submesh and tokens on the data axes, XLA lowers those einsums to the
all-to-all — no hand-written autograd collective is needed.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclass
class MoEConfig:
    num_experts: int
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    top_k: int = 1  # 1 = switch routing, 2 = gshard-style
    aux_loss_weight: float = 0.01
    router_jitter: float = 0.0  # multiplicative logit noise during training


def _capacity(num_tokens: int, num_experts: int, factor: float) -> int:
    return max(1, int(math.ceil(num_tokens * factor / num_experts)))


def router_dispatch(
    logits: jax.Array,  # [T, E]
    capacity: int,
    top_k: int = 1,
    rng: Optional[jax.Array] = None,
    jitter: float = 0.0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compute (dispatch_mask [T,E,C], combine_weights [T,E,C], aux_loss).

    Switch-style: each token goes to its top-k experts, subject to a
    per-expert capacity; overflowing tokens are dropped (their combine
    weight is zero, so the residual path carries them).
    """
    t, e = logits.shape
    if rng is not None and jitter > 0.0:
        noise = jax.random.uniform(
            rng, logits.shape, minval=1.0 - jitter, maxval=1.0 + jitter
        )
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    remaining = probs
    expert_fill = jnp.zeros((e,), jnp.int32)
    total_onehot = jnp.zeros((t, e), jnp.float32)

    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, E]
        # position of each token within its expert's queue (arrival order)
        pos_in_expert = (
            jnp.cumsum(onehot, axis=0) - onehot
        ) * onehot  # [T, E]
        pos_in_expert = pos_in_expert + expert_fill[None, :] * onehot
        within = (pos_in_expert < capacity).astype(jnp.float32) * onehot
        pos = pos_in_expert.sum(axis=-1).astype(jnp.int32)  # [T]
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        gate = (probs * onehot).sum(axis=-1, keepdims=True)  # [T,1]
        # `within` is already zero for dropped/over-capacity tokens
        dispatch = dispatch + within[:, :, None] * pos_oh[:, None, :]
        combine = combine + (
            gate[:, :, None] * within[:, :, None] * pos_oh[:, None, :]
        )
        expert_fill = expert_fill + within.sum(axis=0).astype(jnp.int32)
        total_onehot = total_onehot + onehot
        remaining = remaining * (1.0 - onehot)

    # load-balance auxiliary loss (switch transformer eq. 4)
    frac_tokens = total_onehot.mean(axis=0)  # [E]
    frac_probs = probs.mean(axis=0)  # [E]
    aux_loss = e * jnp.sum(frac_tokens * frac_probs) / max(1, top_k)
    return dispatch, combine, aux_loss


def moe_ffn(
    params: dict,
    x: jax.Array,  # [B, S, D]
    config: MoEConfig,
    activation: Callable = jax.nn.gelu,
    train: bool = True,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Switch-FFN block. params:
      router/kernel: [D, E]
      experts/up/kernel:   [E, D, F]
      experts/down/kernel: [E, F, D]
    Returns (output [B,S,D], aux_loss scalar).
    """
    b, s, d = x.shape
    t = b * s
    xt = x.reshape(t, d)
    logits = xt @ params["router"]["kernel"]  # [T, E]
    factor = config.capacity_factor if train else config.eval_capacity_factor
    capacity = _capacity(t, config.num_experts, factor)
    dispatch, combine, aux = router_dispatch(
        logits, capacity, config.top_k, rng,
        config.router_jitter if train else 0.0,
    )
    # all-to-all #1: tokens -> expert queues (XLA inserts the collective
    # when experts are mesh-sharded). The SPMD partitioner may log an
    # "involuntary full rematerialization" for the [T,1,1] gate broadcast
    # when dispatch/combine consumers want different T shardings — that
    # tensor is tokens*4 bytes, so the replicate-and-repartition it falls
    # back to is noise, not a bandwidth problem.
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch.astype(x.dtype), xt
    )  # [E, C, D]
    h = activation(jnp.einsum(
        "ecd,edf->ecf", expert_in, params["experts"]["up"]["kernel"]
    ))
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["experts"]["down"]["kernel"]
    )  # [E, C, D]
    # all-to-all #2: expert queues -> tokens
    out = jnp.einsum(
        "tec,ecd->td", combine.astype(x.dtype), expert_out
    )
    return out.reshape(b, s, d), aux.astype(jnp.float32)


def init_moe_params(rng, d_model: int, d_ff: int, num_experts: int,
                    dtype=jnp.float32) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "router": {
            "kernel": jax.random.normal(k1, (d_model, num_experts),
                                        dtype) * scale_in,
        },
        "experts": {
            "up": {"kernel": jax.random.normal(
                k2, (num_experts, d_model, d_ff), dtype) * scale_in},
            "down": {"kernel": jax.random.normal(
                k3, (num_experts, d_ff, d_model), dtype) * scale_out},
        },
    }
