"""Shared ppermute ring-step helpers.

The two comm/compute-overlap paths in this tree move data around a mesh
axis with ``lax.ppermute`` rings:

  * ``ops.ring_attention`` rotates KV shards one neighbor per step (the
    classic ring schedule — one ICI hop per step on a TPU torus);
  * ``ops.moe``'s chunked ``grouped_ep`` dispatch decomposes its row
    all-to-all into distance-``s`` permutes so each chunk's exchange can
    overlap the grouped GEMM on the previous chunk's rows.

Both build their permutation tables and axis-size resolution HERE so the
ring mechanics (and their legacy-jax fallbacks) cannot fork between the
call sites.

Why a distance-``s`` permute ring instead of a hop-by-hop relay for the
all-to-all: relaying block ``j`` through every intermediate shard would
put each block on the wire ``dist(i, j)`` times — O(P^2) blocks total —
while one ``ppermute`` per distance moves every block exactly once, so
the ring's total bytes equal the one-shot ``all_to_all``'s minus the
local (diagonal) block that never needs the wire. The G106 byte audit
relies on exactly this parity (``docs/static_analysis.md``).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def ring_axis_size(axis_name) -> int:
    """Size of a (manual) mesh axis from inside shard_map, on either
    jax era: ``lax.axis_size`` when present (>= 0.5), else the
    constant-folded ``psum(1)`` legacy spelling."""
    return (lax.axis_size(axis_name) if hasattr(lax, "axis_size")
            else lax.psum(1, axis_name))


def neighbor_perm(n: int) -> List[Tuple[int, int]]:
    """The single-hop ring permutation (shard i -> i+1): what the KV
    rotation uses every step."""
    return [(i, (i + 1) % n) for i in range(n)]


def shifted_perm(n: int, shift: int) -> List[Tuple[int, int]]:
    """The distance-``shift`` permutation (shard i -> i+shift): one step
    of the ring all-to-all decomposition."""
    return [(i, (i + shift) % n) for i in range(n)]


def ring_shift(x, axis_name, n: int):
    """Rotate ``x`` one neighbor around the ring (a single ICI hop)."""
    return lax.ppermute(x, axis_name, neighbor_perm(n))


def ring_all_to_all(x: jax.Array, axis_name, n: int) -> jax.Array:
    """An ``all_to_all`` over the leading axis, decomposed into ``n-1``
    distance-``s`` ``ppermute`` steps.

    ``x``: ``[n, ...]`` where block ``j`` is the data THIS shard sends
    to shard ``j``. Returns ``[n, ...]`` where block ``j`` is the data
    shard ``j`` sent to THIS shard — the same contract as
    ``lax.all_to_all(x, axis_name, 0, 0)`` with the axis already split.

    The diagonal block (self -> self) never touches the wire; each of
    the other ``n-1`` blocks rides exactly one permute, so total wire
    bytes match the one-shot collective. Because each step's permute has
    no data dependency on any other step, a caller that interleaves
    these exchanges with independent compute (the chunked MoE dispatch)
    gives XLA's latency-hiding scheduler real overlap to find — the
    one-shot ``all_to_all`` is an opaque single op it cannot split.

    Differentiable: ``ppermute`` transposes to the inverse permutation,
    so the backward runs the mirrored ring for free.
    """
    i = lax.axis_index(axis_name)
    # local (diagonal) block: a dynamic slice, no wire traffic
    mine = lax.dynamic_slice_in_dim(x, i, 1, axis=0)
    out = jnp.zeros_like(x)
    out = lax.dynamic_update_slice_in_dim(out, mine, i, axis=0)
    for s in range(1, n):
        # send the block destined to shard (i+s); receive the block
        # shard (i-s) destined to me
        send = lax.dynamic_slice_in_dim(x, (i + s) % n, 1, axis=0)
        recv = lax.ppermute(send, axis_name, shifted_perm(n, s))
        out = lax.dynamic_update_slice_in_dim(
            out, recv, (i - s) % n, axis=0
        )
    return out
