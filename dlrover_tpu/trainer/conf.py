"""Class-based training configuration system.

Role parity: ``dlrover/trainer/util/conf_util.py:48-205``
(``Configuration`` + ``ConfigurationManagerMeta``) — users declare train
configs as Python classes; class attributes merge down the inheritance
chain (subclass wins), registered classes merge by name, and the result
behaves as both attribute- and dict-style config.
"""

from __future__ import annotations

from typing import Any, ClassVar, Dict, List, Optional, Type


def _is_config_attr(name: str) -> bool:
    return not name.startswith("_")


def _class_attrs(cls: type) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    # reversed MRO: base values first, subclasses override
    for klass in reversed(cls.__mro__):
        for name, value in vars(klass).items():
            if _is_config_attr(name) and not callable(value) and not isinstance(
                value, (classmethod, staticmethod, property)
            ):
                out[name] = value
    return out


class Configuration:
    """Attribute/dict hybrid with recursive merge."""

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self._data: Dict[str, Any] = {}
        if data:
            self.merge_dict(data)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_class(cls, conf_cls: type) -> "Configuration":
        return cls(_class_attrs(conf_cls))

    @classmethod
    def from_module(cls, module) -> "Configuration":
        data = {
            k: v for k, v in vars(module).items()
            if _is_config_attr(k) and not callable(v)
            and not isinstance(v, type(module))
        }
        return cls(data)

    # -- access --------------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        data = object.__getattribute__(self, "_data")
        if name in data:
            value = data[name]
            if isinstance(value, dict):
                return Configuration(value)
            return value
        raise AttributeError(name)

    def get(self, name: str, default: Any = None) -> Any:
        return self._data.get(name, default)

    def __getitem__(self, name: str) -> Any:
        return self._data[name]

    def __contains__(self, name: str) -> bool:
        return name in self._data

    def set(self, name: str, value: Any):
        self._data[name] = value

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    # -- merge ---------------------------------------------------------------

    def merge_dict(self, other: Dict[str, Any]):
        _deep_merge(self._data, other)
        return self

    def merge(self, other: "Configuration"):
        return self.merge_dict(other.to_dict())

    def __repr__(self):
        return f"Configuration({self._data!r})"


def _deep_merge(base: Dict, other: Dict):
    for key, value in other.items():
        if (
            key in base
            and isinstance(base[key], dict)
            and isinstance(value, dict)
        ):
            _deep_merge(base[key], value)
        else:
            base[key] = value


class ConfigurationManagerMeta(type):
    """Registry metaclass: every subclass of ``ConfigurationManager``
    self-registers; ``merged_configuration`` folds them in definition
    order (reference: ConfigurationManagerMeta collecting conf classes)."""

    # deliberately ONE registry shared by every manager subclass
    # (ClassVar, not an instance default — DLR005)
    _registry: ClassVar[List[type]] = []

    def __new__(mcls, name, bases, namespace):
        cls = super().__new__(mcls, name, bases, namespace)
        if bases:  # skip the root class itself
            mcls._registry.append(cls)
        return cls

    @classmethod
    def registered(mcls) -> List[type]:
        return list(mcls._registry)

    @classmethod
    def clear(mcls):
        mcls._registry.clear()


class ConfigurationManager(metaclass=ConfigurationManagerMeta):
    """Subclass with class attributes to contribute configuration."""

    @classmethod
    def merged_configuration(cls) -> Configuration:
        conf = Configuration()
        for klass in ConfigurationManagerMeta.registered():
            conf.merge(Configuration.from_class(klass))
        return conf


def build_configuration(
    *sources: Any, overrides: Optional[Dict[str, Any]] = None
) -> Configuration:
    """Fold modules / classes / dicts / Configurations, left to right."""
    conf = Configuration()
    for source in sources:
        if isinstance(source, Configuration):
            conf.merge(source)
        elif isinstance(source, dict):
            conf.merge_dict(source)
        elif isinstance(source, type):
            conf.merge(Configuration.from_class(source))
        else:
            conf.merge(Configuration.from_module(source))
    if overrides:
        conf.merge_dict(overrides)
    return conf
