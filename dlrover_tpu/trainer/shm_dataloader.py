"""Coworker data pipeline: CPU preprocessing processes feeding trainers
through the native shared-memory ring.

Role parity: ``atorch/atorch/data/shm_dataloader.py:38-220``
(``ShmDataloader``) + the coworker machinery in
``atorch/atorch/distributed/distributed.py:41-205``: dedicated CPU
processes run the user's preprocessing and publish ready batches into
shared memory; the trainer process never spends Python time building
batches. Transport is ``native/src/shm_ring.cc`` (C++, process-shared
mutex ring), so the per-batch cost in the trainer is one memcpy.

Also plays the ``GpuPreLoader`` role (``data/preloader.py:8``): on TPU
the host->device overlap comes from ``jax.device_put`` on the *next*
batch while the current step runs (device_put is async under jit).
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.native.shm_ring import (
    RingClosed,
    RingTimeout,
    ShmBatchRing,
)
from dlrover_tpu.trainer.data import DevicePreloader

logger = get_logger("trainer.shm")


_DONE_KEY = "__shm_producer_done__"


def _producer_main(ring_name: str, slot_bytes: int, produce_fn,
                   worker_rank: int, num_workers: int):
    """Runs in a coworker process: produce_fn yields numpy-dict batches."""
    ring = ShmBatchRing.attach(ring_name, slot_bytes=slot_bytes)
    try:
        for batch in produce_fn(worker_rank, num_workers):
            ring.put(batch)
        # end-of-stream sentinel: the consumer closes the ring once every
        # producer has reported done (closing here would cut off slower
        # sibling producers)
        ring.put({_DONE_KEY: np.array([worker_rank], np.int32)})
    except (RingClosed, RingTimeout):
        pass  # consumer went away; exit quietly


class ShmDataLoader:
    """Iterator over batches produced by ``num_workers`` coworker
    processes.

    ``produce_fn(worker_rank, num_workers)`` must be a picklable callable
    yielding dict-of-numpy batches; workers partition the work by rank
    (same contract as torch DataLoader worker sharding).
    """

    def __init__(
        self,
        produce_fn: Callable[[int, int], Iterator[Dict[str, np.ndarray]]],
        num_workers: int = 1,
        slot_bytes: int = 1 << 22,
        n_slots: int = 8,
        name: Optional[str] = None,
        timeout: float = 120.0,
    ):
        self._timeout = timeout
        self.name = name or f"/dlrover_shm_{os.getpid()}_{id(self) & 0xffff}"
        self._ring = ShmBatchRing(
            self.name, slot_bytes=slot_bytes, n_slots=n_slots, owner=True
        )
        ctx = mp.get_context("spawn")
        self._workers = [
            ctx.Process(
                target=_producer_main,
                args=(self.name, slot_bytes, produce_fn, rank, num_workers),
                daemon=True,
            )
            for rank in range(num_workers)
        ]
        for w in self._workers:
            w.start()

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        done = 0
        while True:
            try:
                batch = self._ring.get(timeout=self._timeout)
            except RingClosed:
                return
            except RingTimeout:
                if not any(w.is_alive() for w in self._workers):
                    logger.warning(
                        "all shm producers died; ending stream"
                    )
                    return
                raise
            if _DONE_KEY in batch:
                done += 1
                if done == len(self._workers):
                    self._ring.close()
                    return
                continue
            yield batch

    def qsize(self) -> int:
        return self._ring.qsize()

    def shutdown(self):
        self._ring.close()
        for w in self._workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        self._ring.free()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class DevicePrefetcher(DevicePreloader):
    """Overlap host->device transfer with compute: keeps ``depth`` batches
    in flight via ``put_fn`` (async ``jax.device_put``) on a background
    thread — the shm-path face of the ONE sharding-aware prefetcher
    (``trainer.data.DevicePreloader`` in background mode).

    Inherits the base's data-plane instruments: the
    ``dlrover_data_prefetch_queue_depth`` gauge plus the
    producer/consumer wait histograms (docs/data_pipeline.md), so a
    coworker ring that stops keeping up shows as consumer-wait time
    and a depth pinned at 0 — the input-bound signature — without any
    shm-specific hooks."""

    def __init__(self, batches: Iterator[Any], put_fn: Callable[[Any], Any],
                 depth: int = 2):
        super().__init__(batches, prefetch=depth, put_fn=put_fn,
                         background=True)
