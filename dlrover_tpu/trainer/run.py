"""``tpurun`` — the elastic launcher CLI.

Role parity: ``dlrover-run`` (``dlrover/trainer/torch/elastic_run.py``):
torchrun-flavoured flags, ``--standalone`` boots a local master subprocess,
and if no master is reachable the launcher degrades to running the script
directly (the reference falls back to vanilla torchrun).

Usage:
    tpurun --standalone --nproc_per_node 4 train.py --lr 3e-4
    tpurun --nnodes 2:4 --node_unit 2 --network-check train.py
"""

from __future__ import annotations

import argparse
import os
import re
import select
import subprocess
import sys
import time
from typing import List, Optional, Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.monitor.resource import ResourceMonitor
from dlrover_tpu.agent.training_agent import (
    AgentConfig,
    ElasticTrainingAgent,
)
from dlrover_tpu.agent.worker_group import WorkerSpec
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.rpc.server import addr_connectable

logger = get_logger("trainer.run")


def parse_nnodes(value: str) -> Tuple[int, int]:
    """"2" -> (2,2); "1:4" -> (1,4)."""
    if ":" in value:
        lo, hi = value.split(":", 1)
        return int(lo), int(hi)
    n = int(value)
    return n, n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun", description="dlrover_tpu elastic launcher"
    )
    p.add_argument("--nnodes", default="1",
                   help="node count or MIN:MAX for elasticity")
    p.add_argument("--nproc_per_node", default="auto",
                   help="JAX processes per host ('auto' = 1)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get(NodeEnv.NODE_RANK, "0")))
    p.add_argument("--node_unit", type=int, default=1,
                   help="hosts per TPU slice; worlds stay a multiple")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--standalone", action="store_true",
                   help="boot a local master subprocess")
    p.add_argument("--master_addr",
                   default=os.environ.get(NodeEnv.MASTER_ADDR, ""))
    p.add_argument("--network-check", dest="network_check",
                   action="store_true",
                   help="run the paired allgather probe before training")
    p.add_argument("--probe_platform", default="",
                   help="jax platform for the chip probe (tests: cpu)")
    p.add_argument("--rdzv_waiting_timeout", type=float, default=30.0)
    p.add_argument("--monitor_interval", type=float, default=2.0)
    p.add_argument("--relaunch_on_hang", "--relaunch-on-hang",
                   dest="relaunch_on_hang",
                   type=float, default=0.0, metavar="SECONDS",
                   help="restart workers when no heartbeat lands for this "
                        "many seconds (0 = off); parity with the "
                        "reference's --relaunch_on_hanging mode")
    p.add_argument("--log_dir", default="",
                   help="redirect per-worker stdout/err to this directory")
    p.add_argument("--train_window", type=int, default=None,
                   help="in-flight dispatch window of the async train "
                        "loop (0 = synchronous; workers see it as "
                        "DLROVER_TPU_TRAIN_WINDOW)")
    p.add_argument("--steps_per_call", type=int, default=None,
                   help="optimizer steps fused per compiled call "
                        "(lax.scan multi-step; workers see it as "
                        "DLROVER_TPU_STEPS_PER_CALL)")
    p.add_argument("--dispatch_chunks", type=int, default=None,
                   help="chunked grouped_ep MoE dispatch: split the "
                        "row exchange into this many double-buffered "
                        "ppermute-ring chunks (1 = serial one-shot "
                        "all_to_all; workers see it as "
                        "DLROVER_TPU_DISPATCH_CHUNKS; the runtime "
                        "optimizer retunes it live)")
    p.add_argument("--moe_precision", default=None,
                   choices=["bf16", "fp8", "fp8_qdq"],
                   help="grouped_ep MoE wire precision: fp8 quantizes "
                        "the row exchanges to block-scaled e4m3 "
                        "(values + f32 scales, ~half the wire bytes; "
                        "bf16 fallback when the backend fails the fp8 "
                        "probe); workers see it as "
                        "DLROVER_TPU_MOE_PRECISION and the runtime "
                        "optimizer retunes it live")
    p.add_argument("--fsdp_precision", default=None,
                   choices=["bf16", "fp8", "fp8_qdq"],
                   help="dense FSDP wire precision: fp8 quantizes the "
                        "per-layer param gathers of the scan-over-"
                        "layers to block-scaled e4m3 (values + f32 "
                        "scales, ~1/4 of an f32 gather; dequant-exact, "
                        "gradients untouched; bf16 fallback when the "
                        "backend fails the fp8 probe); workers see it "
                        "as DLROVER_TPU_FSDP_PRECISION and the runtime "
                        "optimizer retunes it live")
    p.add_argument("--grad_precision", default=None,
                   choices=["bf16", "fp8"],
                   help="gradient-path precision: fp8 quantizes the "
                        "per-shard gradient tree with an error-"
                        "feedback residual carried in TrainState "
                        "(bounded drift, G109-ratcheted); a BUILD-time "
                        "knob — workers see it as "
                        "DLROVER_TPU_GRAD_PRECISION; never retuned "
                        "live")
    p.add_argument("--snapshot_replicas", type=int, default=None,
                   help="peer-redundant host snapshots: keep this many "
                        "in-DRAM replicas of each node's snapshot "
                        "regions on master-chosen peers (0 = off; the "
                        "budget admission can degrade below it), "
                        "enabling the checkpoint-free peer-rebuild "
                        "recovery rung (docs/elasticity.md); workers "
                        "and the master see it as "
                        "DLROVER_TPU_SNAPSHOT_REPLICAS")
    p.add_argument("--replica_cadence_steps", type=int, default=None,
                   help="materialized steps between snapshot "
                        "replication pushes (wall-time floored by "
                        "replica_min_interval_secs)")
    p.add_argument("--live_recovery", "--live-recovery",
                   dest="live_recovery", action="store_true",
                   help="absorb survivable membership changes with an "
                        "in-process snapshot -> reshard -> resume "
                        "instead of restarting workers; the agent only "
                        "falls back to a restart after a grace window "
                        "(docs/operations.md)")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve Prometheus /metrics from the agent on "
                        "this port (also DLROVER_TPU_METRICS_PORT; "
                        "0/unset = off)")
    p.add_argument("--events_file", default=None,
                   help="append the structured event timeline (JSONL) "
                        "here; workers inherit it via "
                        "DLROVER_TPU_EVENTS_FILE so one file holds "
                        "the whole job")
    p.add_argument("entrypoint", help="training script or executable")
    p.add_argument("args", nargs=argparse.REMAINDER)
    return p


def _launch_local_master(timeout: float = 30.0) -> Tuple[subprocess.Popen, str]:
    """Spawn ``python -m dlrover_tpu.master.main`` and scrape its addr."""
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "dlrover_tpu.master.main",
         "--platform", "local"],
        stdout=subprocess.PIPE, stderr=None, text=True,
    )
    deadline = time.time() + timeout
    addr = ""
    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [], 0.5)
        if not ready:
            if proc.poll() is not None:
                raise RuntimeError("local master exited during startup")
            continue
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError("local master exited during startup")
            time.sleep(0.1)
            continue
        m = re.match(r"DLROVER_TPU_MASTER_ADDR=(\S+)", line)
        if m:
            addr = m.group(1)
            break
    if not addr:
        proc.terminate()
        raise RuntimeError("local master did not report its address")
    logger.info("standalone master at %s", addr)
    return proc, addr


def _run_without_master(args, script_args: List[str]) -> int:
    """Degraded mode: exec the entrypoint directly (reference falls back to
    torchrun when no master is reachable, ``elastic_run.py:154-171``)."""
    logger.warning("no master reachable; running entrypoint directly")
    cmd = (
        [sys.executable, "-u", args.entrypoint]
        if args.entrypoint.endswith(".py") else [args.entrypoint]
    )
    return subprocess.call(cmd + script_args)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "lint":
        # `tpurun lint [...]` — the pre-submit static-analysis gate
        # (framework AST lint + SPMD graph lint); see
        # docs/static_analysis.md
        from dlrover_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] in ("serve", "requests"):
        # `tpurun serve --addr ...` runs one continuous-batching serve
        # worker; `tpurun requests` renders the router ledger (live
        # --addr / forensic --events) — see docs/serving.md
        from dlrover_tpu.serving.cli import main as serving_main

        return serving_main(argv)
    if argv and argv[0] in ("metrics", "mttr", "goodput", "diagnose",
                            "plan", "attribution", "data", "readiness",
                            "events", "trace", "cache"):
        # `tpurun metrics [--addr host:port]` / `tpurun mttr ...` /
        # `tpurun goodput` / `tpurun diagnose` / `tpurun plan` /
        # `tpurun attribution` / `tpurun data` / `tpurun readiness` /
        # `tpurun cache` — the observability CLI
        # (docs/observability.md)
        from dlrover_tpu.telemetry.cli import main as telemetry_main

        return telemetry_main(argv)
    args = build_parser().parse_args(argv)
    script_args = list(args.args)
    if script_args and script_args[0] == "--":
        script_args = script_args[1:]  # strip only the leading separator
    # dispatch-pipeline knobs ride the worker environment: the Context
    # singleton reads DLROVER_TPU_* overrides at import, so every
    # executor/trainer the entrypoint builds picks them up without code
    # changes (and the degraded no-master path inherits them too)
    if args.train_window is not None:
        os.environ["DLROVER_TPU_TRAIN_WINDOW"] = str(args.train_window)
    if args.steps_per_call is not None:
        os.environ["DLROVER_TPU_STEPS_PER_CALL"] = str(args.steps_per_call)
    if args.dispatch_chunks is not None:
        os.environ["DLROVER_TPU_DISPATCH_CHUNKS"] = str(
            args.dispatch_chunks)
    if args.moe_precision is not None:
        os.environ["DLROVER_TPU_MOE_PRECISION"] = args.moe_precision
    if args.fsdp_precision is not None:
        os.environ["DLROVER_TPU_FSDP_PRECISION"] = args.fsdp_precision
    if args.grad_precision is not None:
        os.environ["DLROVER_TPU_GRAD_PRECISION"] = args.grad_precision
    if args.snapshot_replicas is not None:
        # the MASTER prices the replica plan off this knob and the
        # workers gate their replicator/peer-restore on it, so it must
        # land in the shared environment before either initializes
        os.environ["DLROVER_TPU_SNAPSHOT_REPLICAS"] = str(
            args.snapshot_replicas)
    if args.replica_cadence_steps is not None:
        os.environ["DLROVER_TPU_REPLICA_CADENCE_STEPS"] = str(
            args.replica_cadence_steps)
    if args.live_recovery:
        # workers' executors route survivable changes to the in-process
        # reshard path (Context.live_recovery reads this at import)
        os.environ["DLROVER_TPU_LIVE_RECOVERY"] = "1"
    if args.events_file is not None:
        # workers inherit os.environ (worker_group), so the agent's and
        # every worker's lifecycle edges land in ONE timeline file
        os.environ["DLROVER_TPU_EVENTS_FILE"] = args.events_file
    exporter = None
    if args.metrics_port is not None and args.metrics_port > 0:
        from dlrover_tpu.telemetry.exporter import maybe_start_exporter

        exporter = maybe_start_exporter(port=args.metrics_port)
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    nproc = 1 if args.nproc_per_node == "auto" else int(args.nproc_per_node)
    if nproc < 1:
        print("tpurun: --nproc_per_node must be >= 1", file=sys.stderr)
        return 2

    master_proc = None
    addr = args.master_addr
    try:
        if args.standalone:
            master_proc, addr = _launch_local_master()
        if not addr or not addr_connectable(addr):
            return _run_without_master(args, script_args)

        os.environ[NodeEnv.MASTER_ADDR] = addr
        client = MasterClient(addr, node_id=args.node_rank)
        config = AgentConfig(
            node_rank=args.node_rank,
            node_id=args.node_rank,
            nproc_per_node=nproc,
            min_nodes=min_nodes,
            max_nodes=max_nodes,
            node_unit=args.node_unit,
            max_restarts=args.max_restarts,
            monitor_interval=args.monitor_interval,
            rdzv_waiting_timeout=args.rdzv_waiting_timeout,
            network_check=args.network_check,
            probe_platform=args.probe_platform,
            hang_timeout=args.relaunch_on_hang,
            live_recovery=args.live_recovery,
        )
        spec = WorkerSpec(
            entrypoint=args.entrypoint,
            args=tuple(script_args),
            nproc_per_node=nproc,
            redirect_output=args.log_dir or None,
        )
        monitor = ResourceMonitor(client)
        monitor.start()
        agent = ElasticTrainingAgent(config, spec, client)
        rc = agent.run()
        if args.standalone and args.node_rank == 0:
            client.report_job_exit(success=(rc == 0))
        monitor.stop()
        return rc
    finally:
        if exporter is not None:
            exporter.stop()
        if master_proc is not None:
            time.sleep(0.2)
            master_proc.terminate()


if __name__ == "__main__":
    sys.exit(main())
