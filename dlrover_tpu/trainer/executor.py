"""High-level train_and_evaluate executor with hooks.

Role parity: ``dlrover/trainer/tensorflow/executor/
estimator_executor.py:52-287`` (estimator ``train_and_evaluate`` wrapper
with SessionRunHooks, checkpoint cadence, failover-driven session
restart) and the reporting hooks of ``dlrover/python/elastic_agent/
tensorflow/hooks.py:59-113``.

The TPU shape: the "session" is the compiled SPMD program owned by
``ElasticTrainer``; a restart is recompile+reshard, not process death.
Hooks observe the loop at the same points the TF SessionRunHooks did.
"""

from __future__ import annotations

import collections
import os
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import TrainingExceptionLevel
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.diagnosis.hang_detector import touch_heartbeat
from dlrover_tpu.telemetry import (
    EventKind,
    SpanName,
    emit_event,
    get_registry,
    names as tm,
    span,
)
from dlrover_tpu.telemetry.metrics import percentile_from_counts
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.failover import FailoverClient, TrainingFailover

logger = get_logger("trainer.executor")


class NonFiniteLossError(RuntimeError):
    """Raised when the guardrail sees a NaN/Inf loss or gradient and the
    configured policy is \"halt\"."""


@dataclass
class _Inflight:
    """One dispatched-but-unmaterialized train-step call: ``count``
    optimizer steps ending at ``last_step``, metrics still on device."""

    last_step: int
    count: int
    metrics: Dict[str, Any]


class TrainHook:
    """SessionRunHook parity: override any subset."""

    def begin(self, executor: "TrainExecutor"):
        ...

    def before_step(self, step: int):
        ...

    def after_step(self, step: int, metrics: Dict[str, Any]):
        ...

    def after_evaluate(self, step: int, metrics: Dict[str, Any]):
        ...

    def end(self, executor: "TrainExecutor"):
        ...


class ElasticDataShardReportHook(TrainHook):
    """Report consumed batches so the master completes shards
    (reference hooks.py:97 ``ElasticDataShardReportHook``).

    One BATCH credit per materialized step: ``report_batch_done``
    takes a batch COUNT and multiplies by the client's own batch size
    — passing ``batch_size`` as the count (the old behavior) credited
    ``batch_size²`` records per step, completing shards the worker had
    not actually read and desyncing the master's ledger from reality.
    ``batch_size`` stays accepted for call-site compatibility but the
    client owns the records conversion."""

    def __init__(self, sharding_client, batch_size: int = 0):
        self._client = sharding_client
        self._batch_size = batch_size  # informational only

    def after_step(self, step: int, metrics: Dict[str, Any]):
        try:
            self._client.report_batch_done(1)
        except Exception:  # noqa: BLE001 — reporting must not kill training
            logger.exception("shard report failed")


class ReportModelInfoHook(TrainHook):
    """Report model facts + step speed to the master (reference
    hooks.py:59 ``ReportModelMetricHook``)."""

    def __init__(self, master_client, param_count: int = 0,
                 flops_per_step: float = 0.0, every_steps: int = 20,
                 model_spec=None):
        self._client = master_client
        self._param_count = param_count
        self._flops = flops_per_step
        # optional planner ModelSpec: carries the shape facts (layers,
        # hidden, experts) the master's runtime optimizer needs to
        # price knob families — without them the calibrated spec is a
        # dense placeholder and e.g. dispatch_chunks never competes
        self._model_spec = model_spec
        self._every = max(every_steps, 1)
        reg = get_registry()
        self._c_reports = reg.counter(
            tm.MASTER_REPORTS, help="global-step/model reports sent")
        self._c_report_failures = reg.counter(
            tm.MASTER_REPORT_FAILURES,
            help="reports the master never acked (counted, never raised)")

    def begin(self, executor: "TrainExecutor"):
        if self._param_count <= 0:
            return
        try:
            from dlrover_tpu.common import comm

            spec = self._model_spec
            extra = {}
            if spec is not None:
                extra = dict(
                    hidden_size=int(getattr(spec, "hidden_size", 0)),
                    num_layers=int(getattr(spec, "num_layers", 0)),
                    seq_len=int(getattr(spec, "seq_len", 0)),
                    num_experts=int(getattr(spec, "num_experts", 0)),
                    moe_top_k=int(getattr(spec, "moe_top_k", 1)),
                    ffn_mult=float(getattr(spec, "ffn_mult", 0.0)),
                )
            self._client.report_model_info(comm.ModelInfo(
                num_params=self._param_count,
                flops_per_step=self._flops,
                **extra,
            ))
            self._c_reports.inc()
        except Exception:  # noqa: BLE001
            self._c_report_failures.inc()
            logger.exception("model info report failed")

    def after_step(self, step: int, metrics: Dict[str, Any]):
        # runs at MATERIALIZATION (the executor's lagged window), so the
        # reported step is never ahead of host-visible metrics
        if step % self._every:
            return
        try:
            self._client.report_global_step(step)
            self._c_reports.inc()
        except Exception:  # noqa: BLE001 — a dead master must not kill
            # training; the failure is counted so operators see the gap
            self._c_report_failures.inc()


class NodeRuntimeReportHook(TrainHook):
    """Push node-tagged snapshots of the PR 4 instruments to the master
    every ``runtime_report_steps`` materialized steps — the input of the
    cluster diagnosis plane (``master/monitor/node_series.py``).

    Snapshots are CUMULATIVE histogram bucket counts (the master diffs
    consecutive reports into per-window series), plus window occupancy,
    lagged-metric age, process RSS and accelerator ``bytes_in_use``
    where the backend exposes it.

    The step path only SNAPSHOTS (a few tuple copies) and enqueues; the
    RPC, the ``/proc`` RSS read, and the device memory query run on a
    background daemon sender thread. Backpressure drops the report (the
    next cadence supersedes it) — monitoring must never stall the loop,
    and a dead master is a counted gap, not a crash. The send rate is
    additionally floored by ``min_interval_s`` (default: the master's
    ``seconds_interval_to_report``), so a fast-stepping job cannot
    flood the master — or tax itself — with per-step-scale report
    traffic: reporting overhead scales with WALL time, not step count.
    """

    def __init__(self, master_client, every_steps: Optional[int] = None,
                 registry=None, min_interval_s: Optional[float] = None):
        import queue

        ctx = get_context()
        self._client = master_client
        self._every = int(
            every_steps if every_steps is not None
            else getattr(ctx, "runtime_report_steps", 32))
        self._min_interval = float(
            min_interval_s if min_interval_s is not None
            else getattr(ctx, "seconds_interval_to_report", 15))
        self._last_send = 0.0
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._sender: Optional[threading.Thread] = None
        # the instruments this hook snapshots (same handles the
        # executor observes into); a test may pass a private registry
        # to simulate several nodes in one process
        reg = registry if registry is not None else get_registry()
        self._reg = reg
        self._h_step = reg.histogram(tm.STEP_TIME)
        self._h_dispatch = reg.histogram(tm.STEP_DISPATCH_TIME)
        self._h_sync = reg.histogram(tm.STEP_HOST_SYNC_TIME)
        self._g_window = reg.gauge(tm.DISPATCH_WINDOW_OCCUPANCY)
        self._g_lag = reg.gauge(tm.LAGGED_METRIC_AGE)
        self._c_steps = reg.counter(tm.TRAIN_STEPS)
        self._c_sent = get_registry().counter(
            tm.NODE_RUNTIME_REPORTS,
            help="node runtime snapshots pushed to the master")
        self._c_failed = get_registry().counter(
            tm.NODE_RUNTIME_REPORT_FAILURES,
            help="runtime snapshots the master never acked")
        self._devices = None

    def _rss_mb(self) -> float:
        try:
            import psutil

            return psutil.Process().memory_info().rss / (1024 * 1024)
        except Exception:  # noqa: BLE001 — psutil-less hosts
            logger.debug("psutil rss read failed; using getrusage",
                         exc_info=True)
            import resource

            # ru_maxrss is KB on Linux (peak, not current — good enough)
            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0

    def _device_memory_mb(self):
        """(bytes_in_use MB, headroom MB) summed over local devices —
        each ``None`` when NO backend device exposes the stat: a CPU
        mesh must report the gauge ABSENT, not a fake 0 an operator
        would read as an empty accelerator."""
        try:
            import jax

            if self._devices is None:
                self._devices = jax.local_devices()
            in_use = limit = None
            for d in self._devices:
                stats_fn = getattr(d, "memory_stats", None)
                stats = stats_fn() if stats_fn is not None else None
                if not stats:
                    continue
                if "bytes_in_use" in stats:
                    in_use = (in_use or 0) + int(stats["bytes_in_use"])
                if stats.get("bytes_limit"):
                    limit = (limit or 0) + int(stats["bytes_limit"])
            mb = 1024 * 1024
            headroom = (
                (limit - (in_use or 0)) / mb
                if limit is not None else None
            )
            return (in_use / mb if in_use is not None else None,
                    headroom)
        except Exception:  # noqa: BLE001 — CPU backends return nothing
            logger.debug("device memory_stats unavailable",
                         exc_info=True)
            return None, None

    def _gauge_value(self, name: str):
        """A gauge's value if it EXISTS in this hook's registry, else
        None — attribution gauges are created only once a record was
        captured, so absence genuinely means 'not measured'."""
        getter = getattr(self._reg, "get", None)
        metric = getter(name) if getter is not None else None
        return float(metric.value) if metric is not None else None

    def after_step(self, step: int, metrics: Dict[str, Any]):
        if self._every <= 0 or step % self._every:
            return
        now = time.monotonic()
        if now - self._last_send < self._min_interval:
            return
        self._last_send = now
        import queue

        bounds = getattr(self._h_step, "bounds", None)  # null when off
        counts = self._h_step.snapshot_counts()
        payload = dict(
            step=step,
            steps_total=float(self._c_steps.value),
            bounds=list(bounds) if bounds else None,
            step_time_counts=list(counts) if counts else None,
            dispatch_counts=(
                list(self._h_dispatch.snapshot_counts() or []) or None),
            host_sync_counts=(
                list(self._h_sync.snapshot_counts() or []) or None),
            window_occupancy=float(self._g_window.value),
            lagged_age=float(self._g_lag.value),
            # performance-attribution gauges (None until the executor
            # captured a record — the master exports them per node only
            # when they exist)
            mfu=self._gauge_value(tm.ATTR_MFU),
            exposed_comm_frac=self._gauge_value(
                tm.ATTR_EXPOSED_COMM_FRAC),
            flops_per_step=self._gauge_value(tm.ATTR_FLOPS_PER_STEP),
            peak_hbm_mb=self._gauge_value(tm.ATTR_PEAK_HBM_MB),
            # data plane: the executor's derived input-wait fraction
            # (absent until the first measured window, like the
            # attribution gauges — the master exports it per node only
            # when it exists)
            input_wait_frac=self._gauge_value(tm.INPUT_WAIT_FRAC),
        )
        if self._sender is None or not self._sender.is_alive():
            self._sender = threading.Thread(
                target=self._send_loop, name="node-runtime-report",
                daemon=True,
            )
            self._sender.start()
        try:
            self._queue.put_nowait(payload)
        except queue.Full:
            # sender is behind (slow/dead master): drop — the next
            # cadence's cumulative snapshot supersedes this one
            self._c_failed.inc()

    def _send_loop(self):
        while True:
            payload = self._queue.get()
            if payload is None:
                return
            try:
                payload["rss_mb"] = round(self._rss_mb(), 1)
                in_use_mb, headroom_mb = self._device_memory_mb()
                payload["device_mem_mb"] = (
                    round(in_use_mb, 1) if in_use_mb is not None
                    else None)
                payload["hbm_headroom_mb"] = (
                    round(headroom_mb, 1) if headroom_mb is not None
                    else None)
                if headroom_mb is not None:
                    # worker-local mirror (created only when the stat
                    # exists — absent on CPU, never 0)
                    self._reg.gauge(
                        tm.ATTR_HBM_HEADROOM_MB,
                        help="device HBM bytes_limit - bytes_in_use",
                    ).set(headroom_mb)
                self._client.report_node_runtime(**payload)
                self._c_sent.inc()
            except Exception:  # noqa: BLE001 — a dead master must not
                # kill reporting; the gap is counted for operators
                self._c_failed.inc()
                logger.debug("node runtime report failed",
                             exc_info=True)

    def end(self, executor: "TrainExecutor"):
        """Flush: stop the sender after the queued reports drain (join
        bounded — exit must not hang on a dead master)."""
        if self._sender is None or not self._sender.is_alive():
            return
        try:
            self._queue.put_nowait(None)
        except Exception:  # noqa: BLE001 — full queue: sender is wedged
            logger.debug("runtime report queue full at end", exc_info=True)
            return
        self._sender.join(timeout=5.0)


class SnapshotReplicaHook(TrainHook):
    """Push the node's host-snapshot regions to k master-assigned peers
    on a cadence — the peer-redundancy plane of checkpoint-free
    recovery (``checkpoint.replication``).

    The step path pays ONE ``device_get`` per cadence (the same sync a
    checkpoint save stages, floored by ``replica_min_interval_secs`` so
    a fast-stepping job cannot tax itself); slicing, checksummed
    framing and the per-peer RPC stream run on the replicator's
    background daemon thread, with drop-on-backpressure — replication
    is redundancy, never a stall."""

    def __init__(self, master_client, every_steps: Optional[int] = None,
                 min_interval_s: Optional[float] = None,
                 replicator=None):
        ctx = get_context()
        self._client = master_client
        self._every = int(
            every_steps if every_steps is not None
            else getattr(ctx, "replica_cadence_steps", 16))
        self._min_interval = float(
            min_interval_s if min_interval_s is not None
            else getattr(ctx, "replica_min_interval_secs", 15.0))
        self._last_send = 0.0
        self._executor: Optional["TrainExecutor"] = None
        self.replicator = replicator
        self._owns_replicator = replicator is None

    def begin(self, executor: "TrainExecutor"):
        self._executor = executor
        if self.replicator is not None:
            return
        from dlrover_tpu.checkpoint.replication import SnapshotReplicator

        try:
            self.replicator = SnapshotReplicator(
                self._client,
                node_id=int(getattr(self._client, "node_id", 0)),
            )
        except Exception:  # noqa: BLE001 — a port/bind failure loses
            # redundancy, not the job; the gap is visible in the logs
            logger.exception("snapshot replicator startup failed; "
                             "peer redundancy disabled for this run")

    def after_step(self, step: int, metrics: Dict[str, Any]):
        if self.replicator is None or self._executor is None:
            return
        # prefer the MASTER-computed cluster-wide cadence (one value
        # for every node): a per-node wall floor can drift nodes onto
        # disjoint push-step schedules — a jitter event puts node A on
        # {48, 80, ...} and node B on {64, 96, ...} with no resync —
        # and a rebuild needs ONE step with full owner coverage. The
        # local floor only paces the bootstrap cycles before the first
        # plan (and single-node runs, where alignment is moot).
        plan_cadence = int(getattr(
            self.replicator, "plan_cadence_steps", 0) or 0)
        every = plan_cadence if plan_cadence > 0 else self._every
        if every <= 0 or step % every:
            return
        now = time.monotonic()
        if plan_cadence <= 0 and now - self._last_send < \
                self._min_interval:
            return
        self._last_send = now
        try:
            snap = self._executor._trainer.snapshot(self._executor.state)
        except Exception:  # noqa: BLE001 — a failed snapshot loses one
            # cadence of redundancy, never the step loop
            logger.exception("replica snapshot failed at step %d", step)
            return
        self.replicator.submit(snap.tree, snap.meta, snap.step)

    def end(self, executor: "TrainExecutor"):
        if self.replicator is not None and self._owns_replicator:
            self.replicator.stop()


class OptimizerPlanHook(TrainHook):
    """Poll the master for a runtime-optimizer plan and apply it LIVE.

    The master's re-planner (``master/optimizer``) publishes chosen
    plans through the ``ParallelConfig`` broadcast (a non-empty
    ``plan_id`` marks one). A background daemon thread polls
    ``get_parallel_config`` on a WALL-TIME cadence — a dead master's
    RPC timeout must never park the step loop — and routes a fresh plan
    to ``executor.request_retune`` (live: drain → retune/reshard →
    resume) or ``request_restart`` when the master explicitly asked for
    one. Each plan id is applied at most once per process."""

    def __init__(self, master_client, poll_secs: Optional[float] = None):
        ctx = get_context()
        self._client = master_client
        self._poll = float(
            poll_secs if poll_secs is not None
            else getattr(ctx, "plan_poll_secs", 30.0))
        self._executor: Optional["TrainExecutor"] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen_plan = ""

    def begin(self, executor: "TrainExecutor"):
        self._executor = executor
        if self._poll <= 0:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, name="optimizer-plan-poll",
            daemon=True,
        )
        self._thread.start()

    def _poll_loop(self):
        while not self._stop.wait(self._poll):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — master briefly away
                logger.warning(
                    "optimizer plan poll failed, retrying next cadence "
                    "(%s: %s)", type(e).__name__, e)

    def poll_once(self):
        """One poll (also the test entry): fetch the broadcast config
        and hand any UNSEEN optimizer plan to the executor."""
        if self._executor is None:
            return
        cfg = self._client.get_parallel_config()
        plan_id = getattr(cfg, "plan_id", "") or ""
        if not plan_id or plan_id == self._seen_plan:
            return
        if (
            (getattr(cfg, "serve_slots", 0)
             or getattr(cfg, "serve_prefill_chunk", 0)
             or getattr(cfg, "serve_prefix_pool_pages", -1) >= 0)
            and not cfg.steps_per_call and not cfg.mesh_shape
            and cfg.train_window < 0
            and not getattr(cfg, "dispatch_chunks", 0)
            and not getattr(cfg, "moe_precision", "")
            and not getattr(cfg, "fsdp_precision", "")
            and not getattr(cfg, "restart", False)
        ):
            # a SERVE-ONLY plan (every training knob at its sentinel):
            # addressed to a serve worker sharing this master's
            # broadcast slot. Applying it here would be a no-op apply
            # that ACKS the plan — the master would mark it applied
            # and retract it before the serve worker ever polls it.
            # Mark seen and leave it alone.
            self._seen_plan = plan_id
            return
        self._seen_plan = plan_id
        if getattr(cfg, "restart", False):
            logger.info("optimizer plan %s requests a restart", plan_id)
            self._executor.request_restart()
            return
        import jax

        wants_program = (bool(cfg.steps_per_call) or bool(cfg.mesh_shape)
                         or bool(getattr(cfg, "dispatch_chunks", 0))
                         or bool(getattr(cfg, "moe_precision", ""))
                         or bool(getattr(cfg, "fsdp_precision", "")))
        if wants_program and jax.process_count() > 1:
            # each process polls on its own clock: an in-place program
            # swap applied at different wall times would diverge the
            # collective schedule across hosts (host A dispatching the
            # K=8 fused scan against host B's K=1 program deadlocks the
            # mesh). Until the apply is barriered through a rendezvous,
            # multi-host jobs take only the host-local knob live.
            logger.warning(
                "optimizer plan %s changes the compiled program; "
                "in-place swaps are not synchronized across hosts yet "
                "— applying only train_window", plan_id)
            if cfg.train_window >= 0:
                # host-local knob only, WITHOUT the plan identity: an
                # ack would mark the full K/mesh plan applied on the
                # master (bogus ~1.0x realized + retraction) when its
                # program knobs never took effect
                self._executor.request_retune(
                    train_window=cfg.train_window,
                    trace_id=getattr(cfg, "trace_id", "") or "",
                )
            # negative-ack the program plan so the master blacklists
            # it instead of re-publishing every cooldown window
            self._executor._report_trainer_config(
                plan_id=plan_id, apply_failed=True)
            return
        if getattr(cfg, "moe_dispatch", ""):
            # a dispatch-mode change rebuilds the MODEL (the mode lives
            # in the model config, not a trainer knob) — not appliable
            # live yet, and silently acking it as applied would lie to
            # the decision trail
            logger.warning(
                "optimizer plan %s carries moe_dispatch=%s, which "
                "cannot be applied live yet; ignoring that knob",
                plan_id, cfg.moe_dispatch)
        self._executor.request_retune(
            steps_per_call=(cfg.steps_per_call or None),
            train_window=(cfg.train_window
                          if cfg.train_window >= 0 else None),
            mesh_shape=(dict(cfg.mesh_shape) if cfg.mesh_shape
                        else None),
            dispatch_chunks=(
                getattr(cfg, "dispatch_chunks", 0) or None),
            moe_precision=(
                getattr(cfg, "moe_precision", "") or None),
            fsdp_precision=(
                getattr(cfg, "fsdp_precision", "") or None),
            plan_id=plan_id,
            trace_id=getattr(cfg, "trace_id", "") or "",
            predicted_speedup=float(
                getattr(cfg, "predicted_speedup", 0.0) or 0.0),
            prewarm=bool(getattr(cfg, "prewarm", True)),
        )

    def end(self, executor: "TrainExecutor"):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class TrainExecutor:
    """train_and_evaluate over an ElasticTrainer.

    Args:
      trainer: a prepared-or-not ElasticTrainer.
      train_iter_fn: () -> iterable of batches (re-invoked after restart,
        so elastic data sources re-attach at the current shard).
      eval_fn: optional (state) -> metrics dict.
      conf: Configuration with (all optional) ``train_steps``,
        ``eval_every_steps``, ``log_every_steps``.
    """

    def __init__(
        self,
        trainer: ElasticTrainer,
        train_iter_fn: Callable[[], Iterable],
        eval_fn: Optional[Callable[[Any], Dict]] = None,
        hooks: Optional[List[TrainHook]] = None,
        conf: Optional[Configuration] = None,
        master_client=None,
        failover_client: Optional[FailoverClient] = None,
        reshard_world_fn: Optional[Callable[[], Optional[List[Any]]]] = None,
    ):
        self._trainer = trainer
        self._train_iter_fn = train_iter_fn
        self._eval_fn = eval_fn
        self._hooks = list(hooks or [])
        conf = conf or Configuration()
        ctx = get_context()
        self._train_steps = int(conf.get("train_steps", 0))
        self._eval_every = int(conf.get("eval_every_steps", 0))
        self._log_every = int(conf.get("log_every_steps", 50))
        # NaN/overflow guardrail cadence + policy (reference: the error
        # monitor / report_failure path the torch agent takes on a
        # process error, training.py:426)
        self._check_finite_every = int(conf.get(
            "check_finite_every_steps", ctx.check_finite_every_steps
        ))
        # async dispatch pipeline: up to ``train_window`` step calls stay
        # in flight before the oldest call's metrics are materialized on
        # host; 0 = synchronous (materialize right after each dispatch).
        # Hooks, the finite check, speed logging and master reporting all
        # consume LAGGED host values, so the device queue never drains on
        # Python/RPC overhead — and non-finite detection can fire up to
        # train_window * steps_per_call steps late (rollback unchanged).
        self._train_window = max(0, int(conf.get(
            "train_window", getattr(ctx, "train_window", 4)
        )))
        self._window: "collections.deque[_Inflight]" = collections.deque()
        # monotonic: the speed line must survive wall-clock jumps (NTP
        # slews on long jobs) and a drain/resume boundary
        self._last_log = time.monotonic()
        self._last_materialize = time.monotonic()
        # bucket-count snapshot at the previous speed-log line, so the
        # quoted p50/p95 cover just the last window
        self._log_counts_snapshot = None
        # telemetry handles (null objects when the knob is off — the
        # hot loop carries no branches either way)
        reg = get_registry()
        self._h_step_time = reg.histogram(
            tm.STEP_TIME, help="per-optimizer-step wall time, observed "
                               "at (lagged) materialization")
        self._h_dispatch = reg.histogram(
            tm.STEP_DISPATCH_TIME,
            help="host time dispatching one train-step call")
        self._h_host_sync = reg.histogram(
            tm.STEP_HOST_SYNC_TIME,
            help="host time blocked materializing the oldest in-flight "
                 "call (the pipeline's one device sync)")
        self._g_window = reg.gauge(
            tm.DISPATCH_WINDOW_OCCUPANCY,
            help="in-flight dispatches right after a dispatch")
        self._g_lag = reg.gauge(
            tm.LAGGED_METRIC_AGE,
            help="steps between the newest dispatch and the metrics "
                 "just materialized")
        self._c_steps = reg.counter(
            tm.TRAIN_STEPS, help="optimizer steps materialized")
        self._c_nonfinite = reg.counter(
            tm.NONFINITE_STEPS, help="non-finite steps detected")
        self._c_rollbacks = reg.counter(
            tm.NONFINITE_ROLLBACKS, help="checkpoint rollbacks taken")
        self._c_preempt = reg.counter(
            tm.PREEMPT_NOTICES, help="preemption notices received")
        self._h_eval = reg.histogram(
            tm.EVAL_TIME, help="eval_fn wall time")
        # data plane: host time blocked in next(data_iter) fetching the
        # batch for a dispatch. The derived INPUT_WAIT_FRAC gauge is
        # created lazily at the first MEASURED materialization window
        # (absent-not-zero, same discipline as ATTR_MFU) and rides
        # NodeRuntimeReport into the master's per-node series — the
        # third leg of the bound triad (input/comm/compute).
        self._h_input_wait = reg.histogram(
            tm.INPUT_WAIT_TIME,
            help="host time blocked waiting for the next host batch")
        self._g_input_wait: Optional[Any] = None
        self._input_wait_total = 0.0
        self._input_wait_count = 0
        self._input_wait_mark = 0.0
        self._input_wait_count_mark = 0
        self._input_wait_run_start = 0.0
        # newest dispatched (not yet necessarily materialized) step —
        # the minuend of the lagged-metric age
        self._dispatched_step = 0
        # on-demand device profiling: the profile_signal knob arms a
        # handler that opens one bounded jax.profiler window mid-run
        self._profile_signal = str(conf.get(
            "profile_signal", getattr(ctx, "profile_signal", "")))
        self._profile_requested = False
        # the COMPILED multi-step degree lives on the trainer (it owns
        # the K-step scan program); a conf knob that disagrees can only
        # warn — honoring it would recompile mid-construction
        conf_k = int(conf.get("steps_per_call", 0))
        trainer_k = int(getattr(trainer, "steps_per_call", 1))
        if conf_k and conf_k != trainer_k:
            logger.warning(
                "conf steps_per_call=%d ignored: the trainer was built "
                "with steps_per_call=%d (pass it to ElasticTrainer, or "
                "set DLROVER_TPU_STEPS_PER_CALL before construction)",
                conf_k, trainer_k,
            )
        self._on_nonfinite = str(conf.get("on_nonfinite", ctx.on_nonfinite))
        self._max_rollbacks = int(conf.get("max_nonfinite_rollbacks", 3))
        # xprof trace capture (SURVEY §5 tracing): a bounded window of
        # steps recorded to a directory tensorboard/xprof can open
        self._trace_dir = str(conf.get("trace_dir", ctx.trace_dir))
        self._trace_start = int(conf.get(
            "trace_start_step", ctx.trace_start_step))
        self._trace_steps = int(conf.get(
            "trace_num_steps", ctx.trace_num_steps))
        self._tracing = False
        self._rollbacks = 0
        self._last_metrics: Optional[Dict[str, Any]] = None
        self._master_client = master_client
        # cluster diagnosis: node-tagged runtime snapshots ride the
        # master connection automatically (runtime_report_steps=0 or an
        # explicit hook instance opts out)
        report_steps = int(conf.get(
            "runtime_report_steps",
            getattr(ctx, "runtime_report_steps", 32)))
        if master_client is not None and report_steps > 0 and not any(
            isinstance(h, NodeRuntimeReportHook) for h in self._hooks
        ):
            self._hooks.append(NodeRuntimeReportHook(
                master_client, every_steps=report_steps))
        # peer-redundant host snapshots: when the plane is on
        # (snapshot_replicas > 0) and a master connection exists, the
        # replica hook rides along automatically (an explicit hook
        # instance opts out of the auto-wire)
        replicas = int(conf.get(
            "snapshot_replicas",
            getattr(ctx, "snapshot_replicas", 0)))
        if (
            master_client is not None and replicas > 0
            and hasattr(master_client, "report_replica_endpoint")
            and not any(isinstance(h, SnapshotReplicaHook)
                        for h in self._hooks)
        ):
            self._hooks.append(SnapshotReplicaHook(master_client))
        # runtime-optimizer plan channel: poll the master for published
        # plans and apply them live (plan_poll_secs=0 or an explicit
        # hook instance opts out)
        plan_poll = float(conf.get(
            "plan_poll_secs", getattr(ctx, "plan_poll_secs", 30.0)))
        if (
            master_client is not None and plan_poll > 0
            and hasattr(master_client, "get_parallel_config")
            and not any(isinstance(h, OptimizerPlanHook)
                        for h in self._hooks)
        ):
            self._hooks.append(OptimizerPlanHook(
                master_client, poll_secs=plan_poll))
        # a pending optimizer plan (applied at the next loop boundary,
        # after the window drains) and the post-apply measurement window
        # feeding the OPTIMIZER_APPLIED predicted-vs-realized record
        self._retune_request: Optional[Dict[str, Any]] = None
        self._pending_applied: Optional[Dict[str, Any]] = None
        self._applied_probe_counts = None
        # rolling step-time snapshots (refreshed every plan_measure_steps
        # materialized steps): the pre-apply p50 is measured against the
        # most recent CLOSED window, not the whole-run cumulative
        # histogram — on a long job whose degradation started late, the
        # since-start p50 would be healthy-dominated and the realized
        # speedup meaningless
        self._recent_counts = None
        self._recent_counts_prev = None
        self._plan_measure_steps = max(1, int(conf.get(
            "plan_measure_steps",
            getattr(ctx, "plan_measure_steps", 16))))
        # performance attribution: the per-compiled-program record
        # (telemetry.attribution) fetched lazily at the first
        # materialization — its derived MFU / exposed-comm gauges are
        # created only once a record exists, so absence means
        # "not measured". A program change (retune/reshard) re-arms
        # the fetch.
        self._attr_enabled = bool(conf.get(
            "attribution_enabled",
            getattr(ctx, "attribution_enabled", True)))
        self._attr_record: Optional[Any] = None
        self._attr_pending = self._attr_enabled
        self._g_attr_mfu: Optional[Any] = None
        self._g_attr_exposed: Optional[Any] = None
        # precomputed per-step scalars (set at fetch): the hot-loop
        # derivation is two divisions and two gauge stores, nothing else
        self._attr_compute_s = 0.0
        self._attr_mfu_scale = 0.0
        # time-to-first-materialized-step after TRAIN_START: the
        # trace+compile(+restore) cost, the goodput compile bucket
        self._train_started_mono: Optional[float] = None
        self._restart_requested = False
        # live recovery (the in-process scale path): a survivable
        # membership change drains the window, snapshots to host DRAM,
        # rebuilds the mesh and reshards — all without process death.
        # The knob gates whether the failover monitor may route
        # survivable changes here instead of request_restart.
        self._live_recovery = bool(conf.get(
            "live_recovery", getattr(ctx, "live_recovery", True)
        ))
        self._reshard_requested = False
        self._reshard_devices: Optional[List[Any]] = None
        # multi-host: called at reshard time to renegotiate membership
        # and return the survivor device list (e.g. re-join via
        # MasterRendezvousHandler.renegotiate + jax.distributed re-init,
        # then jax.devices()). None = single-host / tests, where the
        # requester passes the devices explicitly.
        self._reshard_world_fn = reshard_world_fn
        self._failover: Optional[TrainingFailover] = None
        if master_client is not None:
            if failover_client is not None:
                failover_client.init_version()
            self._failover = TrainingFailover(
                master_client, self.request_restart,
                failover_client=failover_client,
                on_reshard=(self.request_live_reshard
                            if self._live_recovery else None),
                mttr_table_fn=self._readiness_mttr_table,
            )
        self.state: Any = None
        self.eval_metrics: Dict[str, Any] = {}
        self._last_eval_step = -1
        # preemption grace (reference design goal: flash checkpoint,
        # docs/blogs/stabilize_llm_training_cn.md:215 — bound lost work
        # by an emergency save, not the periodic cadence)
        self._preempt_grace = bool(conf.get("preemption_grace", True))
        self._preempted: Optional[int] = None
        self._prev_handlers: Dict[int, Any] = {}

    # -- preemption grace ----------------------------------------------------

    def install_preemption_handler(self, signals=None):
        """SIGTERM = a preemption notice (the scheduler's grace window,
        and this framework's own agent stop path,
        ``agent/worker_group.py:186``): finish the in-flight step, flush
        an emergency host-staged checkpoint, then end the run cleanly —
        lost work <= 1 step instead of the periodic save cadence.

        Installed automatically by ``train_and_evaluate`` when the conf
        knob ``preemption_grace`` is true (default); a no-op off the
        main thread (signal handlers are main-thread-only in Python).

        One-shot: the first notice re-arms the previous disposition, so
        a SECOND SIGTERM (an impatient supervisor, or the loop blocked
        outside the step path, e.g. in a stalled data iterator) kills
        the process the ordinary way instead of being swallowed.
        """
        import signal as _signal

        if signals is None:
            signals = (_signal.SIGTERM,)

        def _handler(signum, _frame):
            # flag only — the save runs in the loop, after the jitted
            # step returns (handlers must not touch the device)
            self._preempted = signum
            self._restore_signal_dispositions()
            logger.warning(
                "preemption notice (signal %d): emergency checkpoint "
                "after the in-flight step", signum,
            )

        try:
            for s in signals:
                prev = _signal.signal(s, _handler)
                self._prev_handlers[s] = prev
        except ValueError:
            logger.warning(
                "preemption handler unavailable off the main thread"
            )

    def _restore_signal_dispositions(self):
        """Re-arm whatever handled the signals before install (default:
        terminate) — from the handler itself and from run teardown, so
        the process never ends up SIGTERM-proof."""
        import signal as _signal

        for s, prev in self._prev_handlers.items():
            try:
                _signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._prev_handlers = {}

    def _finish_preempted(self, step: int) -> Dict[str, Any]:
        """Emergency save + clean end. The grace window bounds us
        externally (SIGKILL follows); the save is host-DRAM staged, so
        the commit is a local write, not a slow remote upload."""
        logger.warning("preempted at step %d: flushing emergency "
                       "checkpoint", step)
        t0 = time.time()
        t0_mono = time.monotonic()
        try:
            # same guard as the periodic path (elastic.py step()): a
            # NaN-poisoned state must never become the newest restore
            # target — losing the window beats corrupting the chain
            if self._last_metrics is not None and not self._step_is_finite(
                self._last_metrics
            ):
                logger.error(
                    "skipping emergency checkpoint: non-finite state at "
                    "step %d (an older finite checkpoint remains the "
                    "restore target)", step,
                )
            else:
                self._trainer.save(self.state, force=True)
            saved = self._trainer.latest_checkpoint_step()  # flush
            logger.warning(
                "emergency checkpoint committed at step %s in %.1f s",
                saved, time.time() - t0,
            )
        except Exception:  # noqa: BLE001 — still exit cleanly in grace
            logger.exception("emergency checkpoint failed")
        mirror_timed_out = False
        try:
            # close the async manager even when the save above failed:
            # an earlier in-flight save must be waited on before exit
            mirror_timed_out = bool(self._trainer.finalize())
        except Exception:  # noqa: BLE001
            logger.exception("checkpoint finalize failed")
        if mirror_timed_out:
            logger.error(
                "[CKPT_MIRROR_TIMEOUT] preemption drain: the host-DRAM "
                "staging mirror never committed before exit; a storage-"
                "outage restore will fall back to an older staged step"
            )
        if self._master_client is not None:
            try:
                self._master_client.report_failure(
                    node_rank=getattr(self._master_client, "node_id", 0),
                    restart_count=0,
                    error_data=f"preempted at step {step}",
                    level=TrainingExceptionLevel.NODE_ERROR,
                )
            except Exception:  # noqa: BLE001
                pass
        emit_event(
            EventKind.PREEMPT_DRAIN_DONE,
            error_code="CKPT_MIRROR_TIMEOUT" if mirror_timed_out else "",
            step=step,
            drain_seconds=round(time.monotonic() - t0_mono, 3),
        )
        out = dict(self._last_metrics or {})
        out["preempted"] = True
        out["mirror_timed_out"] = mirror_timed_out
        out["step"] = step  # _finish() contract parity
        for hook in self._hooks:
            hook.end(self)
        return out

    # -- failover ------------------------------------------------------------

    def request_restart(self):
        """Membership changed: finish the current step, then rebuild."""
        self._restart_requested = True

    def _readiness_mttr_table(self) -> Dict[str, float]:
        """The master's predicted-MTTR ladder for THIS node (the
        readiness auditor's calibrated blast-radius pricing), consumed
        by the failover monitor so classify_recovery picks the priced
        rung. Empty dict = master without a readiness plane = unpriced."""
        if self._master_client is None or not hasattr(
            self._master_client, "get_readiness"
        ):
            return {}
        try:
            report = self._master_client.get_readiness(
                node_id=getattr(self._master_client, "node_id", -1))
        except Exception:  # noqa: BLE001 — unpriced beats blocked
            logger.warning("readiness fetch failed; recovery stays unpriced",
                           exc_info=True)
            return {}
        node = str(getattr(self._master_client, "node_id", -1))
        nodes = report.get("nodes") or {}
        per_node = nodes.get(node) or {}
        table = per_node.get("predicted_mttr")
        if not table:
            # never swept under this id: any swept node's ladder is a
            # better price than none (pricer state is cluster-wide)
            for detail in nodes.values():
                if detail.get("predicted_mttr"):
                    table = detail["predicted_mttr"]
                    break
        if not isinstance(table, dict):
            return {}
        return {str(k): float(v) for k, v in table.items()}

    def request_live_reshard(self, devices=None):
        """A SURVIVABLE world change (peer lost with a viable survivor
        world, a scale plan, another node's preemption): drain the
        in-flight window at the next loop boundary, then snapshot →
        reshard → resume inside this process. ``devices``: the survivor
        device subset (None = the full post-change world)."""
        self._reshard_devices = list(devices) if devices is not None else None
        self._reshard_requested = True

    def request_retune(self, steps_per_call: Optional[int] = None,
                       train_window: Optional[int] = None,
                       mesh_shape: Optional[Dict[str, int]] = None,
                       dispatch_chunks: Optional[int] = None,
                       moe_precision: Optional[str] = None,
                       fsdp_precision: Optional[str] = None,
                       plan_id: str = "", trace_id: str = "",
                       predicted_speedup: float = 0.0,
                       prewarm: bool = True):
        """A runtime-optimizer plan arrived (``OptimizerPlanHook``):
        apply it at the next loop boundary — drain the window, then
        retune the host knob (``train_window``) in place and swap the
        compiled program (``steps_per_call`` / ``dispatch_chunks`` /
        ``moe_precision`` / ``fsdp_precision`` / mesh override) through
        the program cache. No process restart."""
        self._retune_request = {
            "steps_per_call": steps_per_call,
            "train_window": train_window,
            "mesh_shape": dict(mesh_shape) if mesh_shape else None,
            "dispatch_chunks": dispatch_chunks,
            "moe_precision": moe_precision,
            "fsdp_precision": fsdp_precision,
            "plan_id": plan_id,
            "trace_id": trace_id,
            "predicted_speedup": float(predicted_speedup or 0.0),
            "prewarm": bool(prewarm),
        }

    def _maybe_restart(self):
        if self._reshard_requested:
            self._reshard_requested = False
            devices = self._reshard_devices
            self._reshard_devices = None
            if devices is None and self._reshard_world_fn is not None:
                # multi-host: renegotiate membership first — the new
                # world's devices are only visible after the re-join
                devices = self._reshard_world_fn()
            if devices is None and not self._world_actually_changed():
                # the failover monitor re-fires while nodes sit at the
                # rendezvous, but without new coordinates (no explicit
                # devices, no reshard_world_fn, ambient world unchanged)
                # a reshard would be a snapshot + device_put onto the
                # IDENTICAL topology — churn, not recovery. Skip; the
                # agent's grace-window fallback restart handles a change
                # this process cannot absorb.
                logger.info(
                    "live reshard requested but the visible world is "
                    "unchanged; skipping (no renegotiated coordinates)"
                )
                return
            # the drain already ran at the loop boundary, so the
            # snapshot inside live_reshard covers the last completed
            # optimizer step — nothing is skipped or replayed
            self.state = self._trainer.live_reshard(
                self.state, devices=devices, reason="executor"
            )
            # a reshard may have swapped the compiled program: the old
            # attribution record no longer describes it
            self._refresh_attribution()
            # the resumed step may be behind the max() the master saw
            # (the snapshot covers the last DRAINED step): reset the
            # speed monitor so its gauge/series track the truth
            self._report_step_reset()
            # the master's optimizer re-plans on world changes: tell it
            # what this worker now actually runs
            self._report_trainer_config()
            return
        if self._retune_request is not None:
            req = self._retune_request
            self._retune_request = None
            self._apply_plan(req)
            return
        if not self._restart_requested:
            return
        self._restart_requested = False
        logger.info("rebuilding training session (membership change)")
        self.state = self._trainer.on_world_change(self.state)
        self._refresh_attribution()

    # -- optimizer plan application ------------------------------------------

    def _window_p50(self, counts, baseline) -> Optional[float]:
        """Step-time p50 over the histogram DELTA between two snapshots
        (baseline None = since the start of the run)."""
        if counts is None:
            return None
        window = (
            [c - b for c, b in zip(counts, baseline)]
            if baseline is not None else list(counts)
        )
        bounds = getattr(self._h_step_time, "bounds", None)
        if not bounds:
            return None
        return percentile_from_counts(bounds, window, 0.50)

    def _mesh_override_from(self, mesh_shape) -> Optional[Any]:
        """The MeshPlan override a plan's mesh_shape asks for — None
        when it matches what the trainer already runs (an identical
        override would only churn the program-cache key)."""
        if not mesh_shape:
            return None
        from dlrover_tpu.parallel.mesh import MESH_AXES, MeshPlan

        wanted = {a: int(mesh_shape.get(a, 1)) for a in MESH_AXES}
        try:
            current = self._trainer.accelerated.strategy.mesh.axis_sizes()
        except (RuntimeError, AttributeError):
            current = None
        if current is not None and {
            a: int(v) for a, v in current.items()
        } == wanted:
            return None
        return MeshPlan(**wanted)

    def _apply_plan(self, req: Dict[str, Any]):
        """Apply one optimizer plan at a drained boundary: host knobs
        retune in place, program knobs swap through the trainer's
        program cache (prewarmed first so the swap itself pays zero
        recompiles). Failure keeps the previous config running — a bad
        plan must never take the job down."""
        from dlrover_tpu.telemetry.trace_context import trace_scope

        plan_id = req.get("plan_id", "")
        with trace_scope(req.get("trace_id") or None):
            self._apply_plan_scoped(req, plan_id)

    def _apply_plan_scoped(self, req: Dict[str, Any], plan_id: str):
        k = req.get("steps_per_call")
        w = req.get("train_window")
        ch = req.get("dispatch_chunks")
        mp = req.get("moe_precision")
        mesh = self._mesh_override_from(req.get("mesh_shape"))
        cur_k = max(1, int(getattr(self._trainer, "steps_per_call", 1)))
        if k is not None and int(k) == cur_k:
            k = None
        cur_c = max(1, int(getattr(
            self._trainer, "dispatch_chunks", 1)))
        if ch is not None and int(ch) == cur_c:
            ch = None
        cur_p = str(getattr(
            self._trainer, "moe_precision", "bf16") or "bf16")
        if mp is not None:
            eff = mp
            normalize = getattr(self._trainer, "_effective_precision",
                                None)
            if normalize is not None:
                eff = normalize(mp)
            if eff != mp:
                # the backend cannot honor the requested wire (fp8
                # probe failed): applying would silently run bf16
                # while acking fp8 — the master would mark the plan
                # applied and re-choose it after every trigger, each
                # cycle paying a futile drain. Negative-ack instead so
                # the knob tuple is blacklisted (the multi-host
                # program-plan precedent).
                logger.warning(
                    "optimizer plan %s wants moe_precision=%s but the "
                    "backend runs %s (fp8 probe failed); negative-"
                    "acking so the master blacklists it", plan_id, mp,
                    eff,
                )
                self._report_trainer_config(plan_id=plan_id,
                                            apply_failed=True)
                return
            if mp == cur_p:
                mp = None
        fp = req.get("fsdp_precision")
        cur_fp = str(getattr(
            self._trainer, "fsdp_precision", "bf16") or "bf16")
        if fp is not None:
            eff_fp = fp
            normalize = getattr(self._trainer, "_effective_precision",
                                None)
            if normalize is not None:
                eff_fp = normalize(fp)
            if eff_fp != fp:
                # same phantom-apply hazard as the MoE wire: a backend
                # failing the fp8 probe would run (and the trainer
                # report) bf16 while the master marks fp8 applied —
                # negative-ack so the knob tuple is blacklisted
                logger.warning(
                    "optimizer plan %s wants fsdp_precision=%s but the "
                    "backend runs %s (fp8 probe failed); negative-"
                    "acking so the master blacklists it", plan_id, fp,
                    eff_fp,
                )
                self._report_trainer_config(plan_id=plan_id,
                                            apply_failed=True)
                return
            if fp == cur_fp:
                fp = None
        needs_program = (k is not None or mesh is not None
                         or ch is not None or mp is not None
                         or fp is not None)
        emit_event(
            EventKind.OPTIMIZER_APPLY_BEGIN, plan_id=plan_id,
            steps_per_call=k, train_window=w, dispatch_chunks=ch,
            moe_precision=mp, fsdp_precision=fp,
            mesh=req.get("mesh_shape") if mesh is not None else None,
            step=int(getattr(self.state, "step", 0)),
        )
        t0 = time.monotonic()
        pre_counts = self._h_step_time.snapshot_counts()
        # baseline: the start of the last CLOSED rolling window (falls
        # back to the since-start histogram early in a short run)
        baseline = (self._recent_counts_prev
                    if self._recent_counts_prev is not None
                    else self._recent_counts)
        if baseline is None:
            baseline = self._applied_probe_counts
        pre_p50 = self._window_p50(pre_counts, baseline)
        recompiled = 0
        prewarmed = False
        try:
            if needs_program:
                if req.get("prewarm", True):
                    prewarmed = self._trainer.prewarm(
                        devices=getattr(self._trainer, "devices", None),
                        steps_per_call=k, mesh=mesh,
                        dispatch_chunks=ch, moe_precision=mp,
                        fsdp_precision=fp,
                    )
                compiles_before = self._trainer.compile_count
                self.state = self._trainer.retune(
                    self.state, steps_per_call=k, mesh=mesh,
                    dispatch_chunks=ch, moe_precision=mp,
                    fsdp_precision=fp,
                )
                recompiled = (
                    self._trainer.compile_count - compiles_before
                )
                self._refresh_attribution()
                self._report_step_reset()
            if w is not None:
                self._train_window = max(0, int(w))
        except Exception:  # noqa: BLE001 — a bad plan must not kill the job
            logger.exception(
                "optimizer plan %s failed to apply; continuing with "
                "the previous config", plan_id,
            )
            emit_event(
                EventKind.OPTIMIZER_APPLY_DONE, error_code="APPLY_FAILED",
                plan_id=plan_id,
                seconds=round(time.monotonic() - t0, 3),
            )
            # negative ack: without it the master re-chooses the same
            # deterministically-failing plan after every cooldown
            # window, stalling the job with a drain + failed rebuild
            # each cycle
            self._report_trainer_config(plan_id=plan_id,
                                        apply_failed=True)
            return
        seconds = time.monotonic() - t0
        # the apply stall (prewarm compile, snapshot/reshard) must not
        # bleed into the FIRST post-apply step's measured wall time —
        # it would poison the realized-speedup window
        self._last_materialize = time.monotonic()
        reg = get_registry()
        reg.counter(
            tm.OPTIMIZER_PLANS_APPLIED,
            help="optimizer plans applied live (no restart)").inc()
        reg.histogram(
            tm.OPTIMIZER_APPLY_TIME,
            help="wall seconds of one live plan application",
        ).observe(seconds)
        emit_event(
            EventKind.OPTIMIZER_APPLY_DONE, plan_id=plan_id,
            seconds=round(seconds, 3), recompiled=recompiled,
            prewarmed=prewarmed, train_window=self._train_window,
            steps_per_call=int(getattr(
                self._trainer, "steps_per_call", 1)),
            dispatch_chunks=int(getattr(
                self._trainer, "dispatch_chunks", 1)),
            moe_precision=str(getattr(
                self._trainer, "moe_precision", "bf16")),
            fsdp_precision=str(getattr(
                self._trainer, "fsdp_precision", "bf16")),
        )
        logger.info(
            "optimizer plan %s applied in %.2fs (recompiled=%d, "
            "prewarmed=%s)", plan_id, seconds, recompiled, prewarmed,
        )
        counts_after = self._h_step_time.snapshot_counts()
        if counts_after is not None:
            self._pending_applied = {
                "plan_id": plan_id,
                "trace_id": req.get("trace_id", ""),
                "predicted_speedup": req.get("predicted_speedup", 0.0),
                "pre_p50": pre_p50,
                "counts_at_apply": counts_after,
                "target_steps": (
                    self._c_steps.value + self._plan_measure_steps),
            }
        # ack the APPLY immediately (so the master marks the decision
        # applied and retracts the broadcast even if the job ends — or
        # telemetry is off — before the measurement window closes); the
        # realized-speedup measurement follows as a best-effort second
        # report from _finish_applied
        self._report_trainer_config(
            plan_id=plan_id,
            predicted_speedup=req.get("predicted_speedup", 0.0),
        )

    def _finish_applied(self, step: int):
        """The post-apply measurement window closed: emit the
        predicted-vs-realized OPTIMIZER_APPLIED record and ack the plan
        to the master."""
        pa = self._pending_applied
        self._pending_applied = None
        if pa is None:
            return
        cur = self._h_step_time.snapshot_counts()
        post_p50 = self._window_p50(cur, pa["counts_at_apply"])
        realized = None
        if pa["pre_p50"] and post_p50:
            realized = round(pa["pre_p50"] / post_p50, 3)
        from dlrover_tpu.telemetry.trace_context import trace_scope

        # re-enter the plan's incident scope: the measurement window
        # closes steps after the apply, but the APPLIED record must
        # join the same decision trail
        with trace_scope(pa.get("trace_id") or None):
            emit_event(
                EventKind.OPTIMIZER_APPLIED, plan_id=pa["plan_id"],
                predicted_speedup=round(pa["predicted_speedup"], 3),
                realized_speedup=realized,
                pre_step_p50_s=pa["pre_p50"], post_step_p50_s=post_p50,
                step=step,
            )
        self._applied_probe_counts = cur
        self._report_trainer_config(
            plan_id=pa["plan_id"],
            predicted_speedup=pa["predicted_speedup"],
            realized_speedup=realized or 0.0,
        )

    def _report_trainer_config(self, plan_id: str = "",
                               predicted_speedup: float = 0.0,
                               realized_speedup: float = 0.0,
                               apply_failed: bool = False):
        """Tell the master what this worker ACTUALLY runs (the runtime
        optimizer's running-config input and plan-apply ack)."""
        if self._master_client is None or not hasattr(
            self._master_client, "report_trainer_config"
        ):
            return
        try:
            result = self._trainer.accelerated
            mesh_shape = {
                a: int(v)
                for a, v in result.strategy.mesh.axis_sizes().items()
            }
            # the MoE dispatch mode lives in the MODEL config; the
            # trainer sees it only through its planner ModelSpec —
            # report it when known so the optimizer's dispatch_chunks
            # family unlocks (it gates on moe_dispatch=="grouped_ep")
            spec = getattr(self._trainer, "_model_spec", None)
            self._master_client.report_trainer_config(
                world=int(result.mesh.devices.size),
                mesh_shape=mesh_shape,
                train_window=int(self._train_window),
                steps_per_call=int(getattr(
                    self._trainer, "steps_per_call", 1)),
                dispatch_chunks=int(getattr(
                    self._trainer, "dispatch_chunks", 1)),
                moe_precision=(
                    str(getattr(self._trainer, "moe_precision",
                                "bf16"))
                    if getattr(spec, "num_experts", 0) else ""),
                moe_dispatch=(
                    getattr(spec, "moe_dispatch", "")
                    if getattr(spec, "num_experts", 0) else ""),
                # the dense-wire knobs are reported only when the
                # trainer carries a planner ModelSpec (the llama-family
                # path that actually implements the wire): an
                # unconditional "bf16" would unpark the optimizer's
                # fsdp_precision family for models whose loss_fn never
                # resolves the knob — a plan the worker acks but the
                # program ignores (the moe_dispatch precedent above)
                fsdp_precision=(
                    str(getattr(self._trainer, "fsdp_precision",
                                "bf16") or "bf16")
                    if spec is not None else ""),
                grad_precision=(
                    str(getattr(self._trainer, "grad_precision",
                                "bf16") or "bf16")
                    if spec is not None else ""),
                global_batch=int(
                    result.strategy.global_batch_size or 0),
                plan_id=plan_id,
                predicted_speedup=float(predicted_speedup or 0.0),
                realized_speedup=float(realized_speedup or 0.0),
                apply_failed=bool(apply_failed),
            )
        except Exception:  # noqa: BLE001 — a dead master must not block
            # training; the optimizer just runs on a staler config view
            logger.debug("trainer config report failed", exc_info=True)

    # -- performance attribution ---------------------------------------------

    def _refresh_attribution(self):
        """The compiled program changed (retune / live reshard /
        restart rebuild): drop the record and re-arm the lazy fetch."""
        self._attr_record = None
        self._attr_pending = self._attr_enabled

    def _fetch_attribution(self):
        """Fetch the trainer's per-program attribution record (once
        per program — the trainer caches it by the program-cache key)
        and export the static gauges. Gauges are CREATED here, not in
        __init__, so a job that never captured a record never exports
        a misleading 0."""
        attribution = getattr(self._trainer, "attribution", None)
        if attribution is None:
            return
        try:
            record = attribution()
        except Exception:  # noqa: BLE001 — observation-only: a capture
            # failure must never take the step loop down
            logger.warning("attribution fetch failed", exc_info=True)
            record = None
        if record is None:
            return
        self._attr_record = record
        # mfu = flops / (step_s * peak) = (flops / peak) / step_s — the
        # same derived_mfu formula, folded to one multiply per step
        self._attr_mfu_scale = (
            record.flops_per_step / record.peak_flops_per_s
            if record.peak_flops_per_s > 0 else 0.0
        )
        self._attr_compute_s = record.predicted_compute_s
        # NB: the DERIVED gauges (mfu, exposed-comm) are created in
        # _observe_attribution at the first MEASURED step — creating
        # them here would export a fake 0.0 for the whole first
        # trace+compile window (minutes at scale), exactly the
        # absent-never-0 invariant the node series depends on
        reg = get_registry()
        reg.gauge(
            tm.ATTR_FLOPS_PER_STEP,
            help="compiled per-device FLOPs per optimizer step",
        ).set(record.flops_per_step)
        reg.gauge(
            tm.ATTR_ARITH_INTENSITY,
            help="compiled FLOPs / bytes-accessed (HBM-bound when low)",
        ).set(record.arithmetic_intensity)
        reg.gauge(
            tm.ATTR_PEAK_HBM_MB,
            help="compiled per-device peak HBM residency (MB)",
        ).set(record.peak_hbm_bytes / (1024 * 1024))
        reg.gauge(
            tm.ATTR_COMM_PREDICTED_S,
            help="predicted per-step collective seconds (all families)",
        ).set(record.predicted_comm_total_s)
        # the capture's AOT compile is a one-off stall: it must not
        # bleed into the NEXT step's measured wall time (same guard as
        # the optimizer-plan apply)
        self._last_materialize = time.monotonic()

    def _observe_attribution(self, per_step: float):
        """Fuse one measured per-step time with the record into the
        derived gauges — two divisions and two gauge stores, the only
        per-step cost the attribution plane carries (the ≤5% paired
        overhead gate in tests/test_attribution.py pins it)."""
        if self._attr_pending:
            self._attr_pending = False
            self._fetch_attribution()
        if self._attr_record is None or per_step <= 0:
            return
        if self._g_attr_mfu is None:
            reg = get_registry()
            self._g_attr_mfu = reg.gauge(
                tm.ATTR_MFU,
                help="live model-FLOPs utilization (compiled FLOPs/"
                     "step over measured step time x device peak)")
            self._g_attr_exposed = reg.gauge(
                tm.ATTR_EXPOSED_COMM_FRAC,
                help="upper bound on the un-overlapped comm share of "
                     "the step (1 - ideal compute s / measured step s)")
        # .set(), not raw attribute stores: if telemetry was toggled
        # off between fetch and here, the lazy creation above handed
        # back the SHARED null-metric singleton — set() is a no-op on
        # it, a direct .value write would poison every null consumer
        inv = 1.0 / per_step
        self._g_attr_mfu.set(self._attr_mfu_scale * inv)
        frac = 1.0 - self._attr_compute_s * inv
        self._g_attr_exposed.set(
            0.0 if frac < 0.0 else (1.0 if frac > 1.0 else frac)
        )

    def _observe_input_wait(self, window_s: float):
        """Derive the input-wait fraction of the just-closed
        materialization window: batch-fetch seconds accumulated since
        the previous materialization over the window's wall time. With
        a deep dispatch window the fetches belong to NEWER steps than
        the one materializing — the fraction is a windowed average that
        converges over a report window, which is exactly the
        granularity the node series diffs at. Cost: one subtraction,
        one division, one gauge store."""
        waited = self._input_wait_total - self._input_wait_mark
        fetches = self._input_wait_count - self._input_wait_count_mark
        self._input_wait_mark = self._input_wait_total
        self._input_wait_count_mark = self._input_wait_count
        if window_s <= 0 or self._input_wait_count == 0:
            # nothing measured yet: the gauge must stay ABSENT — a
            # scrape must never read a fake 0 for an unmeasured window
            return
        if fetches == 0:
            # a window with NO batch fetch (the drain's tail: queued
            # dispatches materialize back-to-back) says nothing about
            # the input pipeline — overwriting the gauge with its 0/0
            # would erase the measurement the last real window made
            return
        if self._g_input_wait is None:
            self._g_input_wait = get_registry().gauge(
                tm.INPUT_WAIT_FRAC,
                help="fraction of the last materialization window the "
                     "host spent blocked waiting for the next batch")
        frac = waited / window_s
        # .set(), never a raw .value store: a telemetry toggle between
        # construction and here lands the lazy creation on the shared
        # null-metric singleton (same invariant as the attribution
        # gauges)
        self._g_input_wait.set(
            0.0 if frac < 0.0 else (1.0 if frac > 1.0 else frac)
        )

    def _report_step_reset(self):
        """Tell the master the true global step REWOUND (rollback / live
        reshard) so ``SpeedMonitor.reset_step`` unpins the monotone
        max() gauge and restarts the speed window."""
        if self._master_client is None:
            return
        try:
            self._master_client.report_global_step(
                int(self.state.step), reset=True)
        except Exception:  # noqa: BLE001 — a dead master must not block
            # the recovery path; the gap only stales the speed gauge
            logger.debug("step reset report failed", exc_info=True)

    def _world_actually_changed(self) -> bool:
        """Whether the ambient device world differs from the mesh the
        trainer is currently compiled for (set-compare on device ids —
        ``mesh_utils`` is free to reorder within a topology)."""
        import jax

        try:
            result = self._trainer.accelerated
        except (RuntimeError, AttributeError):
            return True  # nothing compiled yet: let the rebuild decide
        mesh_devices = result.mesh.devices.flatten().tolist()
        ambient = jax.devices()
        return (
            len(mesh_devices) != len(ambient)
            or {getattr(d, "id", None) for d in mesh_devices}
            != {getattr(d, "id", None) for d in ambient}
        )

    # -- NaN/overflow guardrail ----------------------------------------------

    @staticmethod
    def _step_is_finite(metrics: Dict[str, Any]) -> bool:
        import math

        if "finite" in metrics:
            return bool(metrics["finite"])
        try:
            return math.isfinite(float(metrics.get("loss", 0.0)))
        except (TypeError, ValueError):
            return True

    def _report_nonfinite(self, step: int, metrics: Dict[str, Any]) -> str:
        """Log + report the non-finite step to the master; returns the
        serialized detail for the exception message."""
        import json as _json

        detail = _json.dumps({
            "step": step,
            "loss": repr(metrics.get("loss")),
            "grad_norm": repr(metrics.get("grad_norm")),
            "reason": "non-finite loss/gradients",
        })
        logger.error("non-finite training step: %s", detail)
        self._c_nonfinite.inc()
        emit_event(EventKind.NONFINITE_STEP, error_code="NONFINITE",
                   step=step, policy=self._on_nonfinite)
        if self._master_client is not None:
            try:
                self._master_client.report_failure(
                    node_rank=getattr(self._master_client, "node_id", 0),
                    restart_count=0,
                    error_data=detail,
                    level=TrainingExceptionLevel.PROCESS_ERROR,
                )
            except Exception:  # noqa: BLE001 — never mask the real error
                logger.exception("failed to report non-finite step")
        return detail

    def _handle_nonfinite(self, step: int, metrics: Dict[str, Any]) -> bool:
        """Report the failure and apply the policy. Returns True when the
        loop must re-enter (rollback restored an older state). The whole
        failure → recovery edge runs under one freshly minted incident
        trace id, so the NONFINITE_STEP / ROLLBACK_RESTORED events and
        the master's ingress-side records correlate."""
        from dlrover_tpu.telemetry.trace_context import trace_scope

        with trace_scope():
            return self._handle_nonfinite_scoped(step, metrics)

    def _handle_nonfinite_scoped(self, step: int,
                                 metrics: Dict[str, Any]) -> bool:
        detail = self._report_nonfinite(step, metrics)
        if self._on_nonfinite == "rollback":
            latest = getattr(
                self._trainer, "latest_checkpoint_step", lambda: None
            )()
            if latest is None:
                # no checkpoint manager OR nothing saved yet: "rollback"
                # would silently restart from a fresh random init —
                # escalate instead of losing all progress
                raise NonFiniteLossError(
                    "on_nonfinite=rollback but no checkpoint exists to "
                    f"restore; halting. {detail}"
                )
            self._rollbacks += 1
            if self._rollbacks > self._max_rollbacks:
                raise NonFiniteLossError(
                    f"non-finite step persisted through {self._max_rollbacks}"
                    f" rollbacks; halting. {detail}"
                )
            logger.warning(
                "rolling back to the last checkpoint after non-finite step "
                "(%d/%d)", self._rollbacks, self._max_rollbacks,
            )
            # same world: restore onto the existing compiled program;
            # prepare(None) would recompile the whole step for nothing
            restore = getattr(self._trainer, "restore_state", None)
            restored = restore() if restore is not None else None
            self.state = (restored if restored is not None
                          else self._trainer.prepare(None))
            self._c_rollbacks.inc()
            emit_event(EventKind.ROLLBACK_RESTORED, step=step,
                       restored_step=int(self.state.step),
                       rollback=self._rollbacks)
            self._report_step_reset()
            return True
        if self._on_nonfinite == "ignore":
            return False
        raise NonFiniteLossError(detail)

    # -- loop ----------------------------------------------------------------

    def _take_batches(self, data_iter: Iterator, n: int) -> List[Any]:
        out: List[Any] = []
        for _ in range(n):
            t0 = time.monotonic()
            try:
                batch = next(data_iter)
            except StopIteration:
                break
            # the input-wait clock: with the dispatch window keeping
            # the device busy, host time spent here is the data
            # pipeline failing to stay ahead of the accelerator
            waited = time.monotonic() - t0
            self._input_wait_total += waited
            self._input_wait_count += 1
            self._h_input_wait.observe(waited)
            out.append(batch)
        return out

    def _materialize_oldest(self, handle_nonfinite: bool = True) -> bool:
        """Pop the oldest in-flight call, pull its metrics to host (the
        ONE device sync of the pipeline — it waits only on work that is
        already ``train_window`` calls old), and run the lagged per-step
        consumers: after-step hooks, the finite check, speed logging.
        Returns True when a non-finite step triggered a rollback (the
        remaining in-flight steps descend from the poisoned state, so
        the window is discarded wholesale)."""
        import jax

        entry = self._window.popleft()
        t_sync = time.monotonic()
        with span(SpanName.HOST_SYNC, step=entry.last_step):
            host = jax.device_get(entry.metrics)
        now = time.monotonic()
        self._h_host_sync.observe(now - t_sync)
        if self._train_started_mono is not None:
            # first materialization of the run: its latency is
            # dominated by trace+compile (+restore) — the goodput
            # ledger's compile bucket reads it from this event
            emit_event(EventKind.COMPILE_FIRST_STEP,
                       step=entry.last_step,
                       seconds=round(now - self._train_started_mono, 3))
            self._train_started_mono = None
            # an incident trace id inherited from the agent's
            # environment covers the RECOVERY (startup → first step),
            # not the rest of this worker's life: consume it here so
            # hours-later routine events don't mis-correlate to a
            # closed incident
            from dlrover_tpu.telemetry.trace_context import TRACE_ID_ENV

            os.environ.pop(TRACE_ID_ENV, None)
        # per-step wall time: the interval since the previous
        # materialization, amortized over the steps this call carried
        # (exact for K=1; the group average for a fused K-step call)
        window_s = now - self._last_materialize
        per_step = window_s / max(entry.count, 1)
        self._last_materialize = now
        self._g_lag.set(self._dispatched_step - entry.last_step)
        self._observe_attribution(per_step)
        self._observe_input_wait(window_s)
        touch_heartbeat()
        stacked = entry.count > 1
        for i in range(entry.count):
            s = entry.last_step - entry.count + 1 + i
            if stacked:
                sub = {
                    k: (v[i] if getattr(v, "ndim", 0) > 0 else v)
                    for k, v in host.items()
                }
            else:
                sub = host
            self._last_metrics = sub
            self._h_step_time.observe(per_step)
            self._c_steps.inc()
            if self._c_steps.value % self._plan_measure_steps == 0:
                self._recent_counts_prev = self._recent_counts
                self._recent_counts = self._h_step_time.snapshot_counts()
            if (
                self._pending_applied is not None
                and self._c_steps.value
                >= self._pending_applied["target_steps"]
            ):
                self._finish_applied(s)
            for hook in self._hooks:
                hook.after_step(s, sub)
            if (
                handle_nonfinite
                and self._check_finite_every
                and s % self._check_finite_every == 0
                and not self._step_is_finite(sub)
            ):
                if self._handle_nonfinite(s, sub):
                    self._window.clear()
                    return True
            if self._log_every and s % self._log_every == 0:
                # monotonic, and quantiles from the step-time histogram
                # DELTA since the previous log line: a log_every/dt
                # average under-reports jitter and reads garbage across
                # a drain/resume boundary, and lifetime-cumulative
                # quantiles would stop tracking a late regression once
                # old observations dominate
                dt = time.monotonic() - self._last_log
                self._last_log = time.monotonic()
                quantiles = ""
                cur = self._h_step_time.snapshot_counts()
                if cur is not None:
                    prev = self._log_counts_snapshot
                    self._log_counts_snapshot = cur
                    window_counts = (
                        [c - p for c, p in zip(cur, prev)]
                        if prev is not None else cur
                    )
                    bounds = self._h_step_time.bounds
                    p50 = percentile_from_counts(
                        bounds, window_counts, 0.50)
                    p95 = percentile_from_counts(
                        bounds, window_counts, 0.95)
                    if p50 is not None and p95 is not None:
                        quantiles = (" p50=%.1fms p95=%.1fms"
                                     % (p50 * 1e3, p95 * 1e3))
                logger.info(
                    "step %d loss=%.4f (%.2f steps/s%s)", s,
                    float(sub.get("loss", float("nan"))),
                    self._log_every / max(dt, 1e-9), quantiles,
                )
        return False

    def _trim_window(self, limit: int, handle_nonfinite: bool = True) -> bool:
        while len(self._window) > limit:
            if self._materialize_oldest(handle_nonfinite):
                return True
        return False

    def _drain_window(self, handle_nonfinite: bool = True) -> bool:
        """Materialize every in-flight step (eval/exit/preemption/restart
        boundaries). Returns True when the drain hit a rollback."""
        return self._trim_window(0, handle_nonfinite)

    def train_and_evaluate(self) -> Dict[str, Any]:
        # NB: no heartbeat before the first step — the agent's
        # hang_first_beat_grace covers setup + first-step compile, and an
        # early beat would forfeit it (beaten=True drops the allowance to
        # the bare timeout while the compile is still running)
        if self._preempt_grace:
            self.install_preemption_handler()
        self._install_profile_signal_handler()
        self.state = self._trainer.prepare(self.state)
        # re-arm per run: prepare() may have (re)built the program, and
        # a second run must re-read the trainer's cached record
        self._refresh_attribution()
        for hook in self._hooks:
            hook.begin(self)
        if self._failover is not None:
            self._failover.start()

        step = int(self.state.step)
        self._last_log = time.monotonic()
        self._last_materialize = time.monotonic()
        self._log_counts_snapshot = None
        self._applied_probe_counts = None
        self._recent_counts = None
        self._recent_counts_prev = None
        self._last_eval_step = -1
        self._dispatched_step = step
        self._window.clear()
        self._input_wait_mark = self._input_wait_total
        self._input_wait_count_mark = self._input_wait_count
        self._input_wait_run_start = self._input_wait_total
        self._train_started_mono = time.monotonic()
        emit_event(EventKind.TRAIN_START, step=step,
                   train_window=self._train_window,
                   steps_per_call=max(1, int(getattr(
                       self._trainer, "steps_per_call", 1))))
        self._report_trainer_config()
        # capture the attribution record NOW, before the first dispatch:
        # its AOT compile is compile-side cost (the persistent cache
        # then serves the first step's compile warm) and it lands inside
        # the COMPILE_FIRST_STEP window — never in a steady-state timed
        # region (deep windows materialize their first step long after
        # warmup, where a 0.2s capture would poison throughput gates)
        if self._attr_pending:
            self._attr_pending = False
            self._fetch_attribution()
        try:
            while True:
                # re-read per iterator epoch: a live retune (optimizer
                # plan) changes these between boundary re-entries
                window = self._train_window
                k_call = max(1, int(getattr(
                    self._trainer, "steps_per_call", 1)))
                data_iter = iter(self._train_iter_fn())
                restarted = False
                while True:
                    take = k_call
                    if self._train_steps:
                        take = min(take, self._train_steps - step)
                    group = self._take_batches(data_iter, take)
                    if not group:
                        break  # data source exhausted
                    if len(group) == k_call and k_call > 1:
                        for i in range(k_call):
                            for hook in self._hooks:
                                hook.before_step(step + 1 + i)
                        t_disp = time.monotonic()
                        with span(SpanName.STEP_DISPATCH,
                                  step=step + k_call, k=k_call):
                            self.state, metrics = self._trainer.step_multi(
                                self.state, group
                            )
                        self._h_dispatch.observe(
                            time.monotonic() - t_disp)
                        step += k_call
                        self._window.append(
                            _Inflight(step, k_call, metrics)
                        )
                    else:
                        # a group short of steps_per_call (stream tail,
                        # or the last train_steps remainder) dispatches
                        # as single steps. Under K>1 every prior call
                        # went through the multi-step program, so the
                        # FIRST short group traces+compiles the
                        # single-step jit — minutes at scale; lease a
                        # no-beat window so the hang detector doesn't
                        # misread the compile as a stall
                        if k_call > 1:
                            from dlrover_tpu.diagnosis.hang_detector \
                                import announce_long_phase

                            announce_long_phase(900.0)
                        for batch in group:
                            for hook in self._hooks:
                                hook.before_step(step + 1)
                            t_disp = time.monotonic()
                            with span(SpanName.STEP_DISPATCH,
                                      step=step + 1):
                                self.state, metrics = self._trainer.step(
                                    self.state, batch
                                )
                            self._h_dispatch.observe(
                                time.monotonic() - t_disp)
                            step += 1
                            self._window.append(
                                _Inflight(step, 1, metrics)
                            )
                    self._dispatched_step = step
                    touch_heartbeat()  # hang-relaunch liveness beacon
                    self._update_trace(step)

                    if self._trim_window(window):
                        step = int(self.state.step)
                        restarted = True
                        break  # rollback: fresh iterator + old state
                    # steady-state occupancy (post-trim): 0..train_window
                    self._g_window.set(len(self._window))

                    if self._preempted is not None:
                        self._c_preempt.inc()
                        emit_event(EventKind.PREEMPT_NOTICE,
                                   error_code="PREEMPTED", step=step,
                                   signum=int(self._preempted))
                        # drain first: the emergency save must cover the
                        # last MATERIALIZED (completed-on-device) step,
                        # and the finite guard in _finish_preempted needs
                        # real host metrics to judge
                        self._drain_window(handle_nonfinite=False)
                        return self._finish_preempted(step)

                    if self._eval_every and (
                        step // self._eval_every
                        > (step - len(group)) // self._eval_every
                    ):
                        if self._drain_window():
                            step = int(self.state.step)
                            restarted = True
                            break
                        self._evaluate(step)
                    if self._train_steps and step >= self._train_steps:
                        if self._drain_window():
                            step = int(self.state.step)
                            restarted = True
                            break
                        return self._finish(step)
                    if (self._restart_requested or self._reshard_requested
                            or self._retune_request is not None):
                        if self._drain_window():
                            step = int(self.state.step)
                            restarted = True
                            break
                        self._maybe_restart()
                        restarted = True
                        break  # re-enter with a fresh data iterator
                if not restarted:
                    # data source exhausted: drain, then finish (a drain
                    # that rolled back re-enters with a fresh iterator)
                    if self._drain_window():
                        step = int(self.state.step)
                        continue
                    return self._finish(step)
        finally:
            self._stop_trace_if_open(step)
            self._restore_signal_dispositions()
            if self._failover is not None:
                self._failover.stop()

    def _install_profile_signal_handler(self):
        """Arm the on-demand device-profile window: the configured
        signal (conf/Context ``profile_signal``, e.g. "USR2") requests
        one bounded ``jax.profiler.trace`` capture starting at the next
        step — so a production job can be profiled without a restart
        (``kill -USR2 <worker pid>``). Main-thread-only, like the
        preemption handler; a no-op when the knob is empty."""
        if not self._profile_signal:
            return
        import signal as _signal

        name = self._profile_signal.upper().removeprefix("SIG")
        signum = getattr(_signal, f"SIG{name}", None)
        if signum is None:
            logger.warning("unknown profile_signal %r",
                           self._profile_signal)
            return

        def _handler(_signum, _frame):
            # flag only: start_trace must run from the loop, not a
            # signal frame racing the dispatch path
            self._profile_requested = True

        try:
            self._prev_handlers[signum] = _signal.signal(signum, _handler)
        except ValueError:
            logger.warning(
                "profile_signal handler unavailable off the main thread"
            )

    def _profile_dir(self) -> str:
        return self._trace_dir or os.path.join(
            tempfile.gettempdir(), f"dlrover_tpu_xprof_{os.getpid()}"
        )

    def _update_trace(self, step: int):
        """Start/stop the bounded xprof window around the step counter.
        Capture begins after ``trace_start_step`` completed steps (past
        compile + warmup), or immediately when the profile signal asked
        for a window, and spans ``trace_num_steps`` steps."""
        requested = self._profile_requested
        if not self._tracing and not self._trace_dir and not requested:
            return
        if not self._tracing and (requested or step >= self._trace_start):
            # ">=", not "==": a checkpoint-resumed run enters with the
            # restored global step already past trace_start_step, and
            # profiling a restored production job is a primary use
            import jax

            target = self._profile_dir()
            self._profile_requested = False
            jax.profiler.start_trace(target)
            self._tracing = True
            self._trace_stop_at = step + self._trace_steps
            logger.info("xprof trace started at step %d -> %s", step,
                        target)
        elif self._tracing and step >= self._trace_stop_at:
            self._stop_trace_if_open(step)

    def _stop_trace_if_open(self, step: int):
        """xprof only flushes on stop_trace — also called from the run's
        finally so a window open at exit isn't lost."""
        if not self._tracing:
            return
        import jax

        jax.profiler.stop_trace()
        self._tracing = False
        self._trace_dir = ""  # one window per run
        logger.info("xprof trace stopped after step %d", step)

    def _evaluate(self, step: int):
        if self._eval_fn is None or step == self._last_eval_step:
            return
        self._last_eval_step = step
        # reset the hang clock at eval ENTRY so the allowance covers the
        # eval from its start (a beat after it would land too late)
        touch_heartbeat()
        t0 = time.monotonic()
        with span(SpanName.EVALUATE, step=step):
            self.eval_metrics = self._eval_fn(self.state)
        self._h_eval.observe(time.monotonic() - t0)
        touch_heartbeat()
        logger.info("eval @%d: %s", step, {
            # vector metrics (e.g. moe_expert_load [E]) log as lists;
            # only 0-d values convert to float
            k: (float(v) if getattr(v, "ndim", 0) == 0
                else [round(float(x), 4) for x in v])
            for k, v in self.eval_metrics.items()
        })
        for hook in self._hooks:
            hook.after_evaluate(step, self.eval_metrics)

    def _finish(self, step: int) -> Dict[str, Any]:
        if self._eval_fn is not None:
            self._evaluate(step)
        if self._last_metrics is None or self._step_is_finite(
            self._last_metrics
        ):
            self._trainer.save(self.state, force=True)
        else:
            # the final state is NaN-poisoned (the NaN landed between
            # check cadences, or the policy is "ignore"/"rollback"): a
            # force-save here would make it the newest restore target.
            # Report it, and under "halt" fail the run — a NaN final step
            # must not exit 0 as a success.
            detail = self._report_nonfinite(step, self._last_metrics)
            logger.warning(
                "skipping final checkpoint: last step was non-finite"
            )
            if self._on_nonfinite == "halt":
                raise NonFiniteLossError(f"final step non-finite: {detail}")
        self._trainer.finalize()
        # the run's total input-wait seconds ride the TRAIN_END record:
        # the goodput ledger's input-wait column sums these per worker
        # (a column, not a wall bucket — the wait overlaps train spans)
        emit_event(EventKind.TRAIN_END, step=step,
                   input_wait_s=round(
                       self._input_wait_total
                       - self._input_wait_run_start, 3))
        for hook in self._hooks:
            hook.end(self)
        return {"step": step, **self.eval_metrics}
