"""Training-process bootstrap: env contract -> jax.distributed.

The agent hands every worker process its SPMD coordinates via environment
variables (``NodeEnv``); calling :func:`init_worker` inside the training
script wires them into ``jax.distributed.initialize`` — the TPU-native
replacement for the reference wiring torch's c10d store through the master
(``elastic_agent/torch/master_kv_store.py``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.agent.master_client import (
    MasterClient,
    build_master_client,
)
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger("trainer.bootstrap")


@dataclass
class WorkerContext:
    process_id: int
    num_processes: int
    node_rank: int
    node_num: int
    local_rank: int
    local_world_size: int
    restart_round: int
    coordinator_addr: str
    master_client: Optional[MasterClient]

    @property
    def is_chief(self) -> bool:
        return self.process_id == 0


def init_worker(platform: Optional[str] = None,
                cpu_collectives: str = "gloo") -> WorkerContext:
    """Initialize distributed JAX from the agent's env contract.

    ``platform``: force a jax platform (tests pass "cpu"); None keeps the
    process default (TPU in production).
    """
    import jax

    from dlrover_tpu.utils.compile_cache import enable_compile_cache

    if platform == "cpu" or "cpu" in os.environ.get(
        "JAX_PLATFORMS", ""
    ).lower():
        # silent, portable persistent-cache reloads on CPU; must run
        # before the client boots (no-op afterwards)
        from dlrover_tpu.utils.compile_cache import cap_cpu_isa_for_cache

        cap_cpu_isa_for_cache()

    # persistent XLA cache: a restarted worker recompiling the same
    # program hits disk instead of the compiler (<90 s restore budget)
    enable_compile_cache()

    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu" and cpu_collectives:
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", cpu_collectives
                )
            except Exception:
                pass

    process_id = int(os.environ.get(NodeEnv.PROCESS_ID, "0"))
    num_processes = int(os.environ.get(NodeEnv.NUM_PROCESSES, "1"))
    coordinator = os.environ.get(NodeEnv.COORDINATOR_ADDR, "")
    ctx = WorkerContext(
        process_id=process_id,
        num_processes=num_processes,
        node_rank=int(os.environ.get(NodeEnv.NODE_RANK, "0")),
        node_num=int(os.environ.get(NodeEnv.NODE_NUM, "1")),
        local_rank=int(os.environ.get("LOCAL_RANK", "0")),
        local_world_size=int(os.environ.get("LOCAL_WORLD_SIZE", "1")),
        restart_round=int(os.environ.get(NodeEnv.RESTART_ROUND, "0")),
        coordinator_addr=coordinator,
        master_client=build_master_client(),
    )
    if num_processes > 1 and coordinator:
        logger.info(
            "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
            coordinator, num_processes, process_id,
        )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return ctx
