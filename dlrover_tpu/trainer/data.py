"""Elastic data input: resumable sampler + runtime-adjustable loader.

Role parity: ``dlrover/trainer/torch/elastic_sampler.py:25``
(``ElasticDistributedSampler`` — resumable, world-size-change-aware) and
``elastic_dataloader.py:19`` (``ElasticDataLoader`` — batch size changed
at runtime from a config push).

TPU-first: each *host* feeds its local slice of the global batch; the
sampler partitions the index space by (num_shards, shard_rank) just like
per-host ``tf.data`` sharding, and resuming after a world change re-
partitions the *remaining* indices over the new world. When a master is
present, the dynamic sharding client (``IndexShardingClient``) replaces
static partitioning entirely — faster hosts pull more shards.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import get_registry, names as tm

logger = get_logger("trainer.data")


class ElasticDistributedSampler:
    """Deterministic, resumable index sampler over ``dataset_size``.

    ``state_dict``/``load_state_dict`` carry ``completed_num`` so a restore
    (possibly at a different world size) skips consumed samples — the
    reference's semantics, minus torch.
    """

    def __init__(
        self,
        dataset_size: int,
        num_shards: int = 1,
        shard_rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if num_shards < 1 or not 0 <= shard_rank < num_shards:
            raise ValueError(
                f"bad shard spec rank={shard_rank} of {num_shards}"
            )
        self.dataset_size = dataset_size
        self.num_shards = num_shards
        self.shard_rank = shard_rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.completed_num = 0  # global count of consumed samples

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.completed_num = 0

    def _global_indices(self) -> np.ndarray:
        idx = np.arange(self.dataset_size)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        return idx

    def __iter__(self) -> Iterator[int]:
        indices = self._global_indices()[self.completed_num:]
        if self.drop_last:
            usable = (len(indices) // self.num_shards) * self.num_shards
            indices = indices[:usable]
        else:
            pad = (-len(indices)) % self.num_shards
            if pad and len(indices) > 0:
                # Tile until the pad is covered: near an epoch boundary the
                # remainder can be smaller than the pad, and every shard
                # must yield the same count or SPMD hosts desync.
                reps = -(-pad // len(indices))
                filler = np.tile(indices, reps)[:pad]
                indices = np.concatenate([indices, filler])
        for i in indices[self.shard_rank:: self.num_shards]:
            yield int(i)

    def __len__(self) -> int:
        remaining = self.dataset_size - self.completed_num
        if self.drop_last:
            return remaining // self.num_shards
        return math.ceil(remaining / self.num_shards)

    # -- elasticity ----------------------------------------------------------

    def record_batch(self, global_batch_size: int):
        """Advance the resume cursor by one global batch."""
        self.completed_num += global_batch_size

    def reshard(self, num_shards: int, shard_rank: int):
        """Adopt a new world; remaining indices re-partition cleanly."""
        logger.info(
            "sampler reshard: %d/%d -> %d/%d (completed=%d)",
            self.shard_rank, self.num_shards, shard_rank, num_shards,
            self.completed_num,
        )
        self.num_shards = num_shards
        self.shard_rank = shard_rank

    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "completed_num": self.completed_num,
            "seed": self.seed,
        }

    def load_state_dict(self, state: dict):
        self.epoch = state.get("epoch", 0)
        self.completed_num = state.get("completed_num", 0)
        self.seed = state.get("seed", self.seed)


class ElasticDataLoader:
    """Batched host-side loader with a runtime-adjustable batch size.

    ``dataset`` is anything indexable; ``collate_fn`` stacks samples
    (default: numpy stack over tree leaves). ``set_batch_size`` takes
    effect at the next batch boundary — the reference reads a config file
    pushed by the master; here the agent calls it directly from the
    paral-config RPC.
    """

    def __init__(
        self,
        dataset: Sequence,
        batch_size: int,
        sampler: Optional[ElasticDistributedSampler] = None,
        collate_fn: Optional[Callable[[List[Any]], Any]] = None,
        sharding_client=None,
        drop_last: bool = True,
    ):
        self.dataset = dataset
        self._batch_size = batch_size
        self.sampler = sampler or ElasticDistributedSampler(
            len(dataset), shuffle=False
        )
        self._collate = collate_fn or _default_collate
        # When set, indices come from the master's dynamic sharding
        # service instead of the static sampler.
        self._sharding_client = sharding_client
        # Every emitted batch must have a fixed leading dim: a trailing
        # partial batch recompiles the jitted SPMD step, and with the
        # dynamic sharding client different hosts can see different
        # partial sizes and desync. drop_last=False pads the final batch
        # (wrapping samples) instead of dropping it.
        self._drop_last = drop_last

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def set_batch_size(self, batch_size: int):
        if batch_size > 0 and batch_size != self._batch_size:
            logger.info("batch size %d -> %d", self._batch_size, batch_size)
            self._batch_size = batch_size

    def _index_stream(self) -> Iterator[int]:
        if self._sharding_client is not None:
            yield from self._sharding_client.record_indices()
        else:
            yield from self.sampler

    def __iter__(self) -> Iterator[Any]:
        buf: List[Any] = []
        for idx in self._index_stream():
            buf.append(self.dataset[idx])
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf.clear()
        if buf and not self._drop_last:
            while len(buf) < self._batch_size:  # pad to the fixed shape
                buf.extend(buf[: self._batch_size - len(buf)])
            yield self._collate(buf[: self._batch_size])

    def __len__(self) -> int:
        n, bs = len(self.sampler), max(self._batch_size, 1)
        return n // bs if self._drop_last else -(-n // bs)


def stack_batches(batches: List[Any]):
    """Stack K host batches along a new leading axis (tree-wise) — the
    input shape of ``accelerate``'s ``train_step_multi``."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


class DevicePreloader:
    """Overlap host→device transfer with compute — the ONE H2D
    prefetcher for both data paths (the in-process loader here and the
    shm coworker ring, which wraps it in background mode).

    Role parity: ``atorch/atorch/data/preloader.py:8`` (``GpuPreLoader``
    — a CUDA-stream H2D prefetcher). On TPU, ``jax.device_put`` is
    asynchronous: issuing the transfer for batch N+1 while batch N
    computes hides the PCIe/host time. ``sharding`` may be a
    NamedSharding (the accelerate batch spec) so the prefetch lands
    pre-sharded on the mesh.

    ``global_rows``: the GLOBAL batch row count (e.g.
    ``strategy.global_batch_size``). On a multi-host sharding each
    process feeds its PROCESS-LOCAL rows; with ``global_rows`` known,
    ``put_global_batch`` validates that loudly — a caller feeding the
    global batch on every host would otherwise silently assemble a
    process_count-times larger batch of duplicated rows. 0 skips the
    check (single-process shardings are unaffected either way).

    ``steps_per_call``: K > 1 groups K consecutive batches and stacks
    them along a new leading axis before the device put, so each
    yielded item feeds one ``train_step_multi`` call. Pass the STACKED
    batch spec (``AccelerateResult.stacked_batch_spec``) as
    ``sharding`` in that mode; a trailing group short of K is dropped
    (fixed shapes only — a partial stack would recompile the scan).
    Leave stacking off when the iterator feeds ``TrainExecutor``,
    which does its own grouping.

    ``put_fn``: overrides the transfer entirely (the shm path's hook).
    ``background=True`` runs the puts on a daemon thread feeding a
    bounded queue (depth ``prefetch``) — the shm coworker mode, where
    ring reads must not serialize with the training loop.
    """

    def __init__(self, iterable, sharding=None, prefetch: int = 2,
                 global_rows: int = 0, steps_per_call: int = 1,
                 put_fn: Optional[Callable[[Any], Any]] = None,
                 background: bool = False):
        if prefetch < 1:
            raise ValueError("prefetch must be >= 1")
        if steps_per_call < 1:
            raise ValueError("steps_per_call must be >= 1")
        self._iterable = iterable
        self._sharding = sharding
        self._prefetch = prefetch
        self._global_rows = int(global_rows)
        self._steps_per_call = int(steps_per_call)
        self._put_fn = put_fn
        self._background = background
        # data-plane instruments (null handles when telemetry is off).
        # Queue depth is the prefetcher's health gauge: pinned at 0 the
        # producer can't keep up (input-bound); pinned at `prefetch`
        # the consumer is the bottleneck (healthy). The wait histograms
        # split the same story by direction.
        reg = get_registry()
        self._g_depth = reg.gauge(
            tm.DATA_PREFETCH_QUEUE_DEPTH,
            help="ready batches in the H2D prefetch queue")
        self._h_producer_wait = reg.histogram(
            tm.DATA_PRODUCER_WAIT_TIME,
            help="producer-side wait per batch (foreground: host time "
                 "producing + issuing the next transfer; background: "
                 "time blocked handing a ready batch to a full queue)")
        self._h_consumer_wait = reg.histogram(
            tm.DATA_CONSUMER_WAIT_TIME,
            help="consumer time blocked on an empty prefetch queue "
                 "(the input-bound direction)")
        # background-mode pump state, created ONCE on first iteration:
        # re-entering __iter__ (the executor's restart path) must resume
        # draining the same queue — a second pump racing the first over
        # one shared source iterator would drop and interleave batches
        self._bg_queue = None
        self._bg_done = object()
        self._bg_error: List[BaseException] = []
        self._bg_exhausted = False

    def _put(self, batch):
        if self._put_fn is not None:
            return self._put_fn(batch)
        import jax

        if self._sharding is not None:
            # multi-host shardings assemble from PROCESS-LOCAL rows;
            # fully-addressable ones (incl. every single-process case,
            # any sharding type) stay on plain device_put
            from dlrover_tpu.parallel.accelerate import put_global_batch

            return put_global_batch(
                batch, self._sharding, self._global_rows,
                row_axis=1 if self._steps_per_call > 1 else 0,
            )
        return jax.device_put(batch)

    def _host_items(self):
        """Raw batches, or K-stacked groups when steps_per_call > 1."""
        if self._steps_per_call == 1:
            yield from self._iterable
            return
        group: List[Any] = []
        for batch in self._iterable:
            group.append(batch)
            if len(group) == self._steps_per_call:
                yield stack_batches(group)
                group = []
        if group:
            logger.warning(
                "dropping %d trailing batches short of steps_per_call=%d "
                "(fixed shapes only)", len(group), self._steps_per_call,
            )

    def __iter__(self):
        if self._background:
            yield from self._background_iter()
            return
        import collections

        queue = collections.deque()
        it = iter(self._host_items())
        try:
            for _ in range(self._prefetch):
                queue.append(self._put(next(it)))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            t0 = time.monotonic()
            try:
                queue.append(self._put(next(it)))
            except StopIteration:
                pass
            # foreground mode serializes production with the consumer:
            # this IS the consumer's per-batch input cost (device_put
            # itself is async — the wait is host-side batch assembly)
            self._h_producer_wait.observe(time.monotonic() - t0)
            self._g_depth.set(len(queue))
            yield out

    def _background_iter(self):
        """Puts run on ONE daemon thread feeding a bounded queue:
        ``prefetch`` transfers stay in flight while the consumer
        computes (the shm path's DevicePrefetcher behavior, now
        shared). The pump starts on first iteration and is shared by
        every subsequent ``__iter__`` — re-entry resumes mid-stream."""
        import queue as _queue
        import threading

        if self._bg_queue is None:
            self._bg_queue = _queue.Queue(maxsize=self._prefetch)

            def pump():
                try:
                    for b in self._host_items():
                        item = self._put(b)
                        t0 = time.monotonic()
                        self._bg_queue.put(item)
                        # time blocked on a FULL queue: the consumer is
                        # slower than the pipeline — the healthy shape
                        self._h_producer_wait.observe(
                            time.monotonic() - t0)
                except BaseException as e:  # surface in the consumer
                    logger.warning(
                        "prefetch pump failed (%s); re-raising in the "
                        "consumer", type(e).__name__,
                    )
                    self._bg_error.append(e)
                finally:
                    self._bg_queue.put(self._bg_done)

            threading.Thread(target=pump, daemon=True).start()
        while not self._bg_exhausted:
            t0 = time.monotonic()
            item = self._bg_queue.get()
            # time blocked on an EMPTY queue: the producer is the
            # bottleneck — the input-bound direction
            self._h_consumer_wait.observe(time.monotonic() - t0)
            self._g_depth.set(self._bg_queue.qsize())
            if item is self._bg_done:
                self._bg_exhausted = True
                break
            yield item
        if self._bg_error:
            raise self._bg_error[0]


def _default_collate(samples: List[Any]):
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *samples)
