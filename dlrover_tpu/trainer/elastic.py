"""ElasticTrainer: fixed global batch under a changing world.

Role parity: ``dlrover/trainer/torch/elastic.py:214-407``
(``ElasticTrainer``) — the reference keeps the *global* batch size fixed
under elasticity by setting ``gradient_accumulation_steps =
max_workers / cur_world`` and skipping gradient sync on accumulation
steps.

TPU-first: there is no per-step sync to skip — the train step is one
compiled SPMD program. Elasticity instead means: when the world changes,
re-derive the strategy for the new device count (same global batch, the
``data`` axis shrinks, ``grad_accum_steps`` grows to compensate) and
re-``accelerate``. Checkpoint/restore across the transition is GSPMD-
native (``dlrover_tpu.checkpoint``). The per-step hot loop stays pure.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from dlrover_tpu.checkpoint import (
    CheckpointInterval,
    ElasticCheckpointManager,
    abstract_like,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.accelerate import AccelerateResult, accelerate
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import get_registry, names as tm

logger = get_logger("trainer.elastic")


class ElasticTrainer:
    """Owns the (strategy, compiled step, state) triple across world changes.

    Usage::

        trainer = ElasticTrainer(init_fn, loss_fn, optimizer, example_batch,
                                 strategy, ckpt_dir="/ckpt")
        state = trainer.prepare()          # restores if a checkpoint exists
        for batch in loader:
            state, metrics = trainer.step(state, batch)
        # agent signals a membership change:
        state = trainer.on_world_change(state)   # recompile + reshard
    """

    def __init__(
        self,
        init_fn: Callable,
        loss_fn: Callable,
        optimizer,
        example_batch: Any,
        strategy: Optional[Strategy] = None,
        ckpt_dir: str = "",
        ckpt_interval: Optional[CheckpointInterval] = None,
        master_client=None,
        report_every_steps: int = 10,
        devices=None,
        steps_per_call: Optional[int] = None,
    ):
        self._init_fn = init_fn
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._example_batch = example_batch
        self._base_strategy = strategy or Strategy()
        self._master_client = master_client
        self._report_every = max(report_every_steps, 1)
        # multi-step fusion degree: K>1 compiles an extra K-step scan
        # (accelerate train_step_multi) so the executor can dispatch K
        # optimizer steps per host call. None defers to the global
        # context knob (DLROVER_TPU_STEPS_PER_CALL / tpurun flag).
        if steps_per_call is None:
            from dlrover_tpu.common.config import get_context

            steps_per_call = int(getattr(
                get_context(), "steps_per_call", 1
            ))
        self.steps_per_call = max(1, int(steps_per_call))
        # explicit device set (default: the whole jax.devices() world);
        # the agent hands the post-change survivor subset to
        # on_world_change, and dryruns carve sub-worlds out of one host
        self._devices = list(devices) if devices is not None else None

        self._result: Optional[AccelerateResult] = None
        # Device count the base strategy was written for; grad-accum scales
        # relative to this (the reference's max_workers anchor).
        self._initial_devices: Optional[int] = None
        # Host-side mirror of state.step: reading the device scalar every
        # step would force a host-device sync in the hot loop.
        self._host_step = 0
        self._rng = jax.random.PRNGKey(0)
        reg = get_registry()
        self._c_reports = reg.counter(
            tm.MASTER_REPORTS, help="global-step/model reports sent")
        self._c_report_failures = reg.counter(
            tm.MASTER_REPORT_FAILURES,
            help="reports the master never acked (counted, never raised)")
        self._ckpt: Optional[ElasticCheckpointManager] = None
        if ckpt_dir:
            self._ckpt = ElasticCheckpointManager(
                ckpt_dir, save_interval=ckpt_interval or CheckpointInterval()
            )

    # -- build / rebuild -----------------------------------------------------

    @property
    def accelerated(self) -> AccelerateResult:
        if self._result is None:
            raise RuntimeError("call prepare() first")
        return self._result

    def _build(self, num_devices: int) -> AccelerateResult:
        if self._initial_devices is None:
            self._initial_devices = num_devices
        strategy = self._base_strategy.adjust_to_world(
            num_devices, prev_num_devices=self._initial_devices
        )
        return accelerate(
            self._init_fn,
            self._loss_fn,
            self._optimizer,
            self._example_batch,
            strategy=strategy,
            rng=self._rng,
            devices=self._devices,
            steps_per_call=self.steps_per_call,
        )

    def prepare(self, state: Any = None) -> Any:
        """Compile for the current world; restore or init state."""
        n = len(self._devices) if self._devices else len(jax.devices())
        self._result = self._build(n)
        if state is not None:
            self._host_step = int(state.step)
            return state
        if self._ckpt is not None:
            restored = self._try_restore()
            if restored is not None:
                return restored
        self._host_step = 0
        return self._result.init_fn(self._rng)

    def _try_restore(self) -> Optional[Any]:
        abstract = jax.eval_shape(
            lambda r: self._result.init_fn(r), self._rng
        )
        target = abstract_like(abstract, self._result.state_sharding)
        out = self._ckpt.restore(target)
        if out is None:
            return None
        if out["shard_checkpoint"] and self._master_client is not None:
            # Hand the data-shard state back to the master so the epoch
            # resumes where it left off.
            try:
                from dlrover_tpu.common import comm

                self._master_client.report(
                    comm.ShardCheckpoint(content=out["shard_checkpoint"])
                )
            except Exception:  # noqa: BLE001
                logger.exception("restoring shard checkpoint failed")
        logger.info("resumed from step %d", out["step"])
        self._host_step = int(out["state"].step)
        return out["state"]

    def restore_state(self) -> Optional[Any]:
        """Restore the latest checkpoint onto the EXISTING compiled
        program — the rollback path. The world hasn't changed, so the
        jitted step and shardings stay valid; rebuilding via
        ``prepare(None)`` would pay a full re-accelerate + retrace for
        nothing (minutes at scale, and a silent no-heartbeat window the
        hang detector could misread)."""
        if self._result is None or self._ckpt is None:
            return None
        from dlrover_tpu.diagnosis.hang_detector import announce_long_phase

        announce_long_phase(600.0)  # restore window: not a hang
        return self._try_restore()

    def on_world_change(self, state: Any, devices=None) -> Any:
        """Re-accelerate for the new device count and reshard the state.

        Called by the agent/bootstrap after ``jax.distributed`` re-init.
        The global batch stays fixed: ``Strategy.adjust_to_world`` shrinks
        the data axis and grows grad accumulation to compensate — the
        reference's ``_set_gradient_accumulation_steps`` semantics.
        ``devices``: the surviving device subset (default: the full
        post-re-init ``jax.devices()`` world — an explicit
        construction-time subset is dropped, because after a membership
        change those handles may be stale/dead).
        """
        from dlrover_tpu.diagnosis.hang_detector import announce_long_phase

        announce_long_phase(900.0)  # recompile window: not a hang
        self._devices = list(devices) if devices is not None else None
        n = len(self._devices) if self._devices else len(jax.devices())
        old_accum = self._result.strategy.grad_accum_steps if self._result else 1
        self._result = self._build(n)
        logger.info(
            "world changed -> %d devices; grad_accum %d -> %d",
            n, old_accum, self._result.strategy.grad_accum_steps,
        )
        # Reshard the live state onto the new mesh. device_put with the new
        # NamedShardings is an all-gather/reshard XLA program, not a host
        # round-trip.
        return jax.device_put(state, self._result.state_sharding)

    # -- hot loop ------------------------------------------------------------

    def step(self, state: Any, batch: Any) -> Tuple[Any, Dict]:
        self._rng, step_rng = jax.random.split(self._rng)
        sharded = self._result.shard_batch(batch)
        state, metrics = self._result.train_step(state, sharded, step_rng)
        self._host_step += 1
        step = self._host_step
        if self._master_client is not None and step % self._report_every == 0:
            try:
                from dlrover_tpu.common import comm

                self._master_client.report(
                    comm.GlobalStep(step=step, timestamp=time.time())
                )
                self._c_reports.inc()
            except Exception:  # noqa: BLE001 - reporting must never kill training
                self._c_report_failures.inc()
        if self._ckpt is not None and self._ckpt.interval.should_save(step):
            # never checkpoint a NaN-poisoned state: it would corrupt the
            # rollback/restore target (the one device sync this costs
            # happens only on save steps)
            if "finite" not in metrics or bool(metrics["finite"]):
                self.save(state)
            else:
                logger.warning(
                    "skipping checkpoint at step %d: non-finite state", step
                )
        return state, metrics

    def step_multi(self, state: Any, batches: Any) -> Tuple[Any, Dict]:
        """Dispatch ``steps_per_call`` optimizer steps as ONE compiled
        call (the ``lax.scan`` multi-step of ``accelerate``).

        ``batches``: a sequence of exactly ``steps_per_call`` host
        batches, or a pytree already stacked along a leading K axis
        (e.g. from ``DevicePreloader(steps_per_call=K)``). The rng
        stream advances by one split per optimizer step — identical to
        K calls of ``step`` — so a multi-step run is bit-identical to
        the synchronous loop on the same batch stream. Metrics return
        stacked ``[K, ...]`` leaves.
        """
        k = self.steps_per_call
        multi = self._result.train_step_multi
        if multi is None or k <= 1:
            raise RuntimeError(
                "step_multi needs steps_per_call > 1 at construction "
                f"(got steps_per_call={k})"
            )
        if isinstance(batches, (list, tuple)):
            if len(batches) != k:
                raise ValueError(
                    f"step_multi takes exactly steps_per_call={k} "
                    f"batches, got {len(batches)}"
                )
            from dlrover_tpu.trainer.data import stack_batches

            batches = stack_batches(list(batches))
        import jax.numpy as jnp

        rngs = []
        for _ in range(k):
            self._rng, r = jax.random.split(self._rng)
            rngs.append(r)
        sharded = self._result.shard_batch(batches, stacked=True)
        state, metrics = multi(state, sharded, jnp.stack(rngs))
        prev = self._host_step
        self._host_step += k
        step = self._host_step
        if self._master_client is not None and (
            step // self._report_every > prev // self._report_every
        ):
            try:
                from dlrover_tpu.common import comm

                self._master_client.report(
                    comm.GlobalStep(step=step, timestamp=time.time())
                )
                self._c_reports.inc()
            except Exception:  # noqa: BLE001 - reporting must never kill training
                self._c_report_failures.inc()
                logger.debug("global-step report failed", exc_info=True)
        if self._ckpt is not None and self._ckpt.interval.should_save(step):
            # the finite guard reads the stacked flags — one device sync,
            # only on save steps, covering every step in the group
            finite = metrics.get("finite")
            if finite is None or bool(jnp.all(finite)):
                self.save(state)
            else:
                logger.warning(
                    "skipping checkpoint at step %d: non-finite state "
                    "inside the %d-step group", step, k,
                )
        return state, metrics

    # -- checkpoint ----------------------------------------------------------

    def latest_checkpoint_step(self) -> Optional[int]:
        """Newest restorable step, flushing any in-flight async save
        first; None when no checkpointing is configured or nothing has
        been committed yet (the executor's rollback precondition)."""
        if self._ckpt is None:
            return None
        try:
            self._ckpt.wait()
        except Exception:  # noqa: BLE001
            logger.exception("flushing async checkpoint failed")
        return self._ckpt.latest_step()

    def save(self, state: Any, force: bool = True):
        if self._ckpt is None:
            return
        shard_ckpt = ""
        if self._master_client is not None:
            try:
                from dlrover_tpu.common import comm

                resp = self._master_client.get(
                    comm.ShardCheckpointRequest(dataset_name="")
                )
                shard_ckpt = getattr(resp, "content", "") or ""
            except Exception:  # noqa: BLE001
                pass
        self._ckpt.save(
            int(state.step),
            state,
            metadata={"strategy": self._result.strategy.to_json()},
            shard_checkpoint=shard_ckpt,
            force=force,
        )

    def finalize(self) -> bool:
        """Flush + close checkpointing. Returns True when a staging
        mirror timed out (``ElasticCheckpointManager.wait``) — surfaced
        so exit paths (preemption drain) can report that the host-DRAM
        mirror never committed."""
        timed_out = False
        if self._ckpt is not None:
            timed_out = bool(self._ckpt.wait())
            self._ckpt.close()
        return timed_out
