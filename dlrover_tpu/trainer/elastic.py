"""ElasticTrainer: fixed global batch under a changing world.

Role parity: ``dlrover/trainer/torch/elastic.py:214-407``
(``ElasticTrainer``) — the reference keeps the *global* batch size fixed
under elasticity by setting ``gradient_accumulation_steps =
max_workers / cur_world`` and skipping gradient sync on accumulation
steps.

TPU-first: there is no per-step sync to skip — the train step is one
compiled SPMD program. Elasticity instead means: when the world changes,
re-derive the strategy for the new device count (same global batch, the
``data`` axis shrinks, ``grad_accum_steps`` grows to compensate) and
re-``accelerate``. Checkpoint/restore across the transition is GSPMD-
native (``dlrover_tpu.checkpoint``). The per-step hot loop stays pure.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from dlrover_tpu.checkpoint import (
    CheckpointInterval,
    ElasticCheckpointManager,
    HostSnapshot,
    abstract_like,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.accelerate import AccelerateResult, accelerate
from dlrover_tpu.parallel.mesh import topology_key
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import (
    EventKind,
    SpanName,
    emit_event,
    get_registry,
    names as tm,
    span,
)

logger = get_logger("trainer.elastic")


class ElasticTrainer:
    """Owns the (strategy, compiled step, state) triple across world changes.

    Usage::

        trainer = ElasticTrainer(init_fn, loss_fn, optimizer, example_batch,
                                 strategy, ckpt_dir="/ckpt")
        state = trainer.prepare()          # restores if a checkpoint exists
        for batch in loader:
            state, metrics = trainer.step(state, batch)
        # agent signals a membership change:
        state = trainer.on_world_change(state)   # recompile + reshard
    """

    def __init__(
        self,
        init_fn: Callable,
        loss_fn: Callable,
        optimizer,
        example_batch: Any,
        strategy: Optional[Strategy] = None,
        ckpt_dir: str = "",
        ckpt_interval: Optional[CheckpointInterval] = None,
        master_client=None,
        report_every_steps: int = 10,
        devices=None,
        steps_per_call: Optional[int] = None,
        model_spec=None,
        dispatch_chunks: Optional[int] = None,
        moe_precision: Optional[str] = None,
        fsdp_precision: Optional[str] = None,
        grad_precision: Optional[str] = None,
    ):
        self._init_fn = init_fn
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._example_batch = example_batch
        # optional planner ModelSpec: when known, the attribution
        # record's per-collective comm seconds come from the planner's
        # predicted_collective_bytes formula instead of the compiled
        # HLO's own byte parse (telemetry.attribution)
        self._model_spec = model_spec
        self._base_strategy = strategy or Strategy()
        self._master_client = master_client
        self._report_every = max(report_every_steps, 1)
        # multi-step fusion degree: K>1 compiles an extra K-step scan
        # (accelerate train_step_multi) so the executor can dispatch K
        # optimizer steps per host call. None defers to the global
        # context knob (DLROVER_TPU_STEPS_PER_CALL / tpurun flag).
        if steps_per_call is None:
            from dlrover_tpu.common.config import get_context

            steps_per_call = int(getattr(
                get_context(), "steps_per_call", 1
            ))
        self.steps_per_call = max(1, int(steps_per_call))
        # grouped_ep chunked-dispatch degree: a COMPILED-program knob
        # like steps_per_call (the program-cache key carries it, and
        # retune/prewarm swap it live). The model reads it from the
        # Context at trace time (ops.moe.resolve_dispatch_chunks), so
        # _build pins the Context knob to this trainer's value before
        # any build — and the lazy jit trace that follows — runs.
        if dispatch_chunks is None:
            from dlrover_tpu.common.config import get_context

            dispatch_chunks = int(getattr(
                get_context(), "dispatch_chunks", 1))
        self.dispatch_chunks = max(1, int(dispatch_chunks))
        # MoE wire precision: the same COMPILED-program trace-time knob
        # contract as dispatch_chunks (the program-cache key carries
        # it, _build pins the Context knob, retune/prewarm swap it
        # live through the cache)
        if moe_precision is None:
            from dlrover_tpu.common.config import get_context

            moe_precision = str(getattr(
                get_context(), "moe_precision", "bf16") or "bf16")
        self.moe_precision = self._effective_precision(moe_precision)
        # dense FSDP wire precision: the same trace-time program-cache
        # contract as moe_precision (the key carries |fp=, _build pins
        # the Context knob, prewarm/retune swap it live) — normalized
        # through the SAME capability probe so key/report/pricing agree
        # with the traced program
        if fsdp_precision is None:
            from dlrover_tpu.common.config import get_context

            fsdp_precision = str(getattr(
                get_context(), "fsdp_precision", "bf16") or "bf16")
        self.fsdp_precision = self._effective_precision(fsdp_precision)
        # gradient-path precision (error-feedback residual): a BUILD-
        # time knob — it changes the TrainState STRUCTURE, so it is
        # pinned at construction and never enumerated for live retunes
        # (a plan carrying a different value is negative-acked by the
        # executor). The program-cache key still carries |gp= so
        # distinct builds never collide.
        from dlrover_tpu.parallel.accelerate import resolve_grad_precision

        self.grad_precision = resolve_grad_precision(grad_precision)
        # explicit device set (default: the whole jax.devices() world);
        # the agent hands the post-change survivor subset to
        # on_world_change, and dryruns carve sub-worlds out of one host
        self._devices = list(devices) if devices is not None else None
        # runtime-optimizer mesh override: a specific factorization for
        # the CURRENT world (e.g. trade data for fsdp) chosen by the
        # master's re-planner, applied via retune(). None = the base
        # strategy's adjust_to_world derivation.
        self._mesh_override = None

        self._result: Optional[AccelerateResult] = None
        # Compiled-program cache, keyed by (mesh topology, multi-step
        # degree, mesh override): a live reshard BACK to a program this
        # trainer already compiled for (scale down on a failure, scale
        # up when the node returns, a retune back to earlier knobs)
        # reuses the whole AccelerateResult — jitted step(s), shardings,
        # mesh — with ZERO recompiles. Bounded: each entry pins its
        # compiled executables in host memory, and elastic jobs
        # oscillate between a handful of worlds, not dozens.
        self._programs: "collections.OrderedDict[str, AccelerateResult]" = (
            collections.OrderedDict()
        )
        self._program_cache_cap = 4
        # per-compiled-program attribution records, keyed by the SAME
        # program-cache key (captured lazily on first request, evicted
        # with the program). A failed capture caches False so a broken
        # backend is probed once per program, not once per step.
        self._attr_records: Dict[str, Any] = {}
        self._current_program_key: Optional[str] = None
        # accelerate() invocations that actually compiled (cache misses)
        self.compile_count = 0
        # Device count the base strategy was written for; grad-accum scales
        # relative to this (the reference's max_workers anchor).
        self._initial_devices: Optional[int] = None
        # Host-side mirror of state.step: reading the device scalar every
        # step would force a host-device sync in the hot loop.
        self._host_step = 0
        self._rng = jax.random.PRNGKey(0)
        reg = get_registry()
        self._c_reports = reg.counter(
            tm.MASTER_REPORTS, help="global-step/model reports sent")
        self._c_report_failures = reg.counter(
            tm.MASTER_REPORT_FAILURES,
            help="reports the master never acked (counted, never raised)")
        self._ckpt: Optional[ElasticCheckpointManager] = None
        if ckpt_dir:
            self._ckpt = ElasticCheckpointManager(
                ckpt_dir, save_interval=ckpt_interval or CheckpointInterval()
            )

    @staticmethod
    def _effective_precision(precision: Optional[str]) -> str:
        """The wire precision the traced program will ACTUALLY run:
        the probe fallback applied HERE, not just inside ops.moe — so
        the program-cache key, the Context pin, the worker's
        TrainerConfigReport and the planner spec all agree with the
        compiled program. Without this, a backend that fails the fp8
        probe would run the bf16 wire while the trainer reports (and
        the optimizer prices, applies and 'realizes') a phantom fp8."""
        p = (precision or "bf16").strip() or "bf16"
        if p != "bf16":
            from dlrover_tpu.ops.shard_compat import fp8_wire_supported

            if not fp8_wire_supported():
                logger.warning(
                    "moe precision %r requested but the backend fails "
                    "the fp8 probe; the trainer runs (and reports) "
                    "the bf16 wire", p,
                )
                return "bf16"
        return p

    # -- build / rebuild -----------------------------------------------------

    @property
    def accelerated(self) -> AccelerateResult:
        if self._result is None:
            raise RuntimeError("call prepare() first")
        return self._result

    @property
    def devices(self) -> Optional[list]:
        """The explicit device subset this trainer runs on (None = the
        whole ambient world) — what a same-world prewarm must target."""
        return list(self._devices) if self._devices is not None else None

    def _resolved_strategy(self, num_devices: int):
        """The strategy a build for ``num_devices`` will actually
        compile: the base strategy's world derivation, with the
        optimizer's mesh override (when set and it fits) replacing the
        derived factorization."""
        strategy = self._base_strategy.adjust_to_world(
            num_devices, prev_num_devices=self._initial_devices
        )
        if self._mesh_override is not None:
            try:
                strategy = dataclasses.replace(
                    strategy,
                    mesh=self._mesh_override.resolve(num_devices),
                )
            except ValueError:
                # the override was chosen for a different world size:
                # fall back to the derived mesh rather than fail the
                # rebuild (the optimizer re-plans for the new world)
                logger.warning(
                    "mesh override %s does not fit %d devices; using "
                    "the derived mesh", self._mesh_override, num_devices,
                )
        return strategy

    def _program_key(self, devices: list, strategy) -> str:
        """Program-cache identity: device topology x the knobs that
        change the compiled program (multi-step degree, RESOLVED mesh
        factorization). Keyed on what the build will actually compile —
        not on how the knobs were requested — so a retune back to the
        startup config hits the program the trainer began with."""
        from dlrover_tpu.parallel.mesh import mesh_axes_key

        return (
            topology_key(devices)
            + f"|k={self.steps_per_call}"
            + f"|mesh={mesh_axes_key(strategy.mesh)}"
            + f"|c={self.dispatch_chunks}"
            + f"|p={self.moe_precision}"
            + f"|fp={self.fsdp_precision}"
            + f"|gp={self.grad_precision}"
        )

    def _build(self, devices: Optional[list]) -> AccelerateResult:
        """Compile (or fetch from the program cache) for ``devices``
        (None = the whole ``jax.devices()`` world)."""
        actual = list(devices) if devices else jax.devices()
        num_devices = len(actual)
        if self._initial_devices is None:
            self._initial_devices = num_devices
        # pin the trace-time knob BEFORE anything compiles (jit is
        # lazy: the trace may land on the first post-build step, so the
        # Context value must persist — on a prewarm/failed retune the
        # caller restores it alongside self.dispatch_chunks)
        from dlrover_tpu.common.config import get_context

        get_context().dispatch_chunks = self.dispatch_chunks
        get_context().moe_precision = self.moe_precision
        get_context().fsdp_precision = self.fsdp_precision
        strategy = self._resolved_strategy(num_devices)
        key = self._program_key(actual, strategy)
        self._current_program_key = key
        reg = get_registry()
        cached = self._programs.get(key)
        if cached is not None:
            # LRU touch: the topology we are running on must be the
            # last evicted when the cap trims standby entries
            self._programs.move_to_end(key)
            reg.counter(
                tm.PROGRAM_CACHE_HITS,
                help="rebuilds served from the compiled-program cache "
                     "(zero recompiles)").inc()
            logger.info("program cache hit for %d devices (zero "
                        "recompiles)", num_devices)
            return cached
        reg.counter(
            tm.PROGRAM_CACHE_MISSES,
            help="rebuilds that had to compile").inc()
        result = accelerate(
            self._init_fn,
            self._loss_fn,
            self._optimizer,
            self._example_batch,
            strategy=strategy,
            rng=self._rng,
            devices=devices,
            steps_per_call=self.steps_per_call,
            grad_precision=self.grad_precision,
        )
        self.compile_count += 1
        self._programs[key] = result
        while len(self._programs) > self._program_cache_cap:
            evicted, _ = self._programs.popitem(last=False)
            self._attr_records.pop(evicted, None)
            logger.info("program cache evicted topology %.40s...", evicted)
        return result

    def attribution(self):
        """The performance-attribution record for the CURRENT compiled
        program (``telemetry.attribution.AttributionRecord``), captured
        lazily through the AOT path and cached by the program-cache key
        — a retune back to a seen knob set reuses the record like it
        reuses the program. None when attribution/telemetry is off, no
        program is built yet, or the capture failed (probed once)."""
        from dlrover_tpu.telemetry import attribution as attr_mod

        if self._result is None or not attr_mod.attribution_enabled():
            return None
        key = self._current_program_key or ""
        cached = self._attr_records.get(key)
        if cached is not None:
            return cached or None  # False = a probed, failed capture
        try:
            record = attr_mod.capture_attribution(
                self._result,
                steps_per_call=self.steps_per_call,
                example_batch=self._example_batch,
                model_spec=self._model_spec,
                mesh_plan=getattr(self._result.strategy, "mesh", None),
            )
        except Exception:  # noqa: BLE001 — attribution is observation-
            # only: a backend without AOT analysis must not kill the job
            logger.warning("attribution capture failed for this "
                           "program", exc_info=True)
            record = None
        self._attr_records[key] = record if record is not None else False
        return record

    def prepare(self, state: Any = None) -> Any:
        """Compile for the current world; restore or init state.

        Restore ladder (docs/elasticity.md): peer rebuild first — the
        checkpoint-free path that streams state out of surviving peers'
        DRAM (``checkpoint.replication``), taken when replicas are
        configured and at least as fresh as the newest checkpoint —
        then the Orbax/host-mirror restore, then a fresh init."""
        self._result = self._build(self._devices)
        if state is not None:
            self._host_step = int(state.step)
            return state
        restored = self._try_peer_restore()
        if restored is not None:
            return restored
        if self._ckpt is not None:
            restored = self._try_restore()
            if restored is not None:
                return restored
        self._host_step = 0
        return self._result.init_fn(self._rng)

    def _try_restore(self) -> Optional[Any]:
        abstract = jax.eval_shape(
            lambda r: self._result.init_fn(r), self._rng
        )
        target = abstract_like(abstract, self._result.state_sharding)
        out = self._ckpt.restore(target)
        if out is None:
            return None
        if out["shard_checkpoint"] and self._master_client is not None:
            # Hand the data-shard state back to the master so the epoch
            # resumes where it left off.
            try:
                from dlrover_tpu.common import comm

                self._master_client.report(
                    comm.ShardCheckpoint(content=out["shard_checkpoint"])
                )
            except Exception:  # noqa: BLE001
                logger.exception("restoring shard checkpoint failed")
        logger.info("resumed from step %d", out["step"])
        self._host_step = int(out["state"].step)
        return out["state"]

    def _try_peer_restore(self) -> Optional[Any]:
        """The checkpoint-free recovery path: ask the master which live
        peers hold replicated snapshot regions, stream them (chunked,
        checksummed, holder-fallback), and ``device_put`` the rebuilt
        host tree against THIS mesh's shardings — the same
        sharding-agnostic landing an Orbax reshard-on-load performs,
        minus the storage round-trip. Returns the rebuilt state, or
        None to degrade to the storage path (no replicas configured,
        none reachable, structure mismatch, or the peers' snapshot is
        STALER than the newest committed checkpoint)."""
        from dlrover_tpu.common.config import get_context

        ctx = get_context()
        if (
            self._master_client is None
            or not getattr(ctx, "peer_restore", True)
            or int(getattr(ctx, "snapshot_replicas", 0)) <= 0
            or not hasattr(self._master_client, "get_recovery_plan")
        ):
            return None
        from dlrover_tpu.checkpoint import replication as repl
        from dlrover_tpu.diagnosis.hang_detector import announce_long_phase

        try:
            plan = self._master_client.get_recovery_plan()
        except Exception as e:  # noqa: BLE001 — no master, no peers:
            # the storage ladder below still recovers the job
            logger.warning("recovery plan fetch failed (%s: %s); taking "
                           "the storage path", type(e).__name__, e)
            return None
        owners = {
            int(k): list(v or [])
            for k, v in (plan.get("owners") or {}).items()
        }
        if not owners or not any(owners.values()):
            return None
        # the master's priced recovery ladder (readiness auditor): the
        # predicted MTTR of each rung, calibrated from realized
        # incidents and push-cycle bandwidth. Absent on old masters —
        # every priced decision below degrades to the ladder order.
        mttr_table: Dict[str, float] = {}
        for rung, secs in (plan.get("predicted_mttr") or {}).items():
            try:
                mttr_table[str(rung)] = float(secs)
            except (TypeError, ValueError):
                continue
        predicted_s = mttr_table.get("peer_rebuild")
        announce_long_phase(600.0)  # rebuild window: not a hang
        abstract = jax.eval_shape(
            lambda r: self._result.init_fn(r), self._rng
        )
        flat, treedef = jax.tree_util.tree_flatten(abstract)
        # the plane's ONE fast-fail channel policy (a dead holder must
        # fall through to the next replica quickly, not burn the
        # patient master backoff ladder)
        channel_factory, close_channels = repl.replica_channel_factory()
        t0 = time.monotonic()
        try:
            # cheap inventory sweep first: the candidate step is known
            # BEFORE any chunk moves, so the staleness gate below can
            # veto the transfer without paying for it
            all_endpoints = [ep for eps in owners.values() for ep in eps]
            inventories = repl._collect_inventories(
                all_endpoints, channel_factory)
            found = repl.best_common_step(inventories)
            if found is None:
                raise repl.PeerRestoreError(
                    "no step with full owner coverage on any "
                    "reachable holder")
            peek_step = found[0]
            # staleness gate: a frozen replicator (expired cadence)
            # must not roll the job back past a newer committed
            # checkpoint — the one storage touch here is a step
            # LISTING, not a state transfer
            if self._ckpt is not None:
                try:
                    ckpt_step = self._ckpt.latest_step()
                except Exception:  # noqa: BLE001 — unreachable storage
                    # cannot veto the in-DRAM copy on offer
                    logger.warning("checkpoint step listing failed "
                                   "during peer restore", exc_info=True)
                    ckpt_step = None
                if ckpt_step is not None and int(ckpt_step) > peek_step:
                    emit_event(EventKind.PEER_REBUILD_FALLBACK,
                               error_code="REPLICA_STALE",
                               replica_step=int(peek_step),
                               checkpoint_step=int(ckpt_step))
                    logger.warning(
                        "peer snapshot step %d is staler than "
                        "checkpoint step %d; restoring from storage",
                        peek_step, ckpt_step)
                    return None
                # priced-rung gate: when an equally fresh checkpoint
                # exists AND the master's calibrated ladder prices the
                # storage restore cheaper than the peer fetch (e.g. a
                # local NVMe cache vs a congested link), take the
                # cheaper rung — the ladder order is a prior, the
                # price is evidence
                storage_pred = mttr_table.get("storage_restore")
                if (ckpt_step is not None
                        and int(ckpt_step) >= peek_step
                        and predicted_s is not None
                        and storage_pred is not None
                        and storage_pred < predicted_s):
                    emit_event(EventKind.PEER_REBUILD_FALLBACK,
                               error_code="MTTR_PRICED_OUT",
                               rung="storage_restore",
                               predicted_mttr_s=round(storage_pred, 3),
                               peer_predicted_mttr_s=round(
                                   predicted_s, 3))
                    logger.info(
                        "storage restore priced at %.2fs beats peer "
                        "rebuild at %.2fs for step %d; taking the "
                        "storage rung", storage_pred, predicted_s,
                        peek_step)
                    return None
            # the failure edge opens only once the gates passed and a
            # transfer actually begins: a by-design degradation (stale
            # replica, nothing reachable) must not strand an unpaired
            # PEER_REBUILD_BEGIN that the MTTR derivation would report
            # as an unrecovered incident
            begin_fields: Dict[str, Any] = {}
            if predicted_s is not None:
                begin_fields["predicted_mttr_s"] = round(predicted_s, 3)
                begin_fields["rung"] = "peer_rebuild"
            emit_event(EventKind.PEER_REBUILD_BEGIN,
                       step=int(peek_step), owners=sorted(owners),
                       holders=sum(len(v) for v in owners.values()),
                       **begin_fields)
            leaves, meta, step, wire_bytes = repl.fetch_tree(
                flat, owners, channel_factory,
                inventories=inventories)
        except repl.PeerRestoreError as e:
            emit_event(EventKind.PEER_REBUILD_FALLBACK,
                       error_code="PEER_RESTORE_UNAVAILABLE",
                       detail=str(e)[:300])
            logger.warning("peer rebuild unavailable (%s); degrading to "
                           "the storage restore path", e)
            return None
        finally:
            close_channels()
        fetch_s = time.monotonic() - t0
        t1 = time.monotonic()
        from dlrover_tpu.checkpoint.manager import _rematerialize

        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        state = jax.device_put(tree, self._result.state_sharding)
        # donation safety: on CPU, device_put can zero-copy ALIAS the
        # fetched numpy buffers — the first donated step would scribble
        # host memory XLA does not own (the Orbax adjacency lesson)
        state = _rematerialize(state)
        jax.block_until_ready(state)
        put_s = time.monotonic() - t1
        self._host_step = int(meta.get("host_step", step))
        rng = meta.get("rng")
        if rng:
            import numpy as np

            self._rng = jax.numpy.asarray(
                np.asarray(rng, dtype=np.uint32))
        reg = get_registry()
        reg.histogram(
            tm.PEER_REBUILD_TIME,
            help="checkpoint-free rebuild: peer fetch + device_put "
                 "wall seconds").observe(fetch_s + put_s)
        reg.counter(
            tm.PEER_REBUILD_BYTES,
            help="bytes streamed out of peer DRAM during rebuilds",
        ).inc(wire_bytes)
        # predicted-vs-realized stamped on the recovery event itself:
        # the readiness plane EMA-corrects its pricer against exactly
        # this pair, and `tpurun mttr --predict` reports the ratio
        done_fields: Dict[str, Any] = {
            "realized_mttr_s": round(fetch_s + put_s, 3),
            "rung": "peer_rebuild",
        }
        if predicted_s is not None:
            done_fields["predicted_mttr_s"] = round(predicted_s, 3)
        emit_event(EventKind.PEER_REBUILD_DONE, step=int(step),
                   fetch_seconds=round(fetch_s, 3),
                   put_seconds=round(put_s, 3),
                   bytes_from_peers=int(wire_bytes), storage_bytes=0,
                   owners=sorted(owners), **done_fields)
        logger.info(
            "peer rebuild: restored step %d from surviving peers' DRAM "
            "(%.1f MB over the wire in %.2fs, device_put %.2fs, zero "
            "storage reads)", step, wire_bytes / 1e6, fetch_s, put_s)
        return state

    def restore_state(self) -> Optional[Any]:
        """Restore the latest checkpoint onto the EXISTING compiled
        program — the rollback path. The world hasn't changed, so the
        jitted step and shardings stay valid; rebuilding via
        ``prepare(None)`` would pay a full re-accelerate + retrace for
        nothing (minutes at scale, and a silent no-heartbeat window the
        hang detector could misread)."""
        if self._result is None or self._ckpt is None:
            return None
        from dlrover_tpu.diagnosis.hang_detector import announce_long_phase

        announce_long_phase(600.0)  # restore window: not a hang
        return self._try_restore()

    def snapshot(self, state: Any) -> HostSnapshot:
        """Host-DRAM copy of the live state (one ``device_get``). The
        reshard source of ``live_reshard``, a rollback anchor that
        survives the loss of any peer's devices, and — with the rng
        stream and host step in its meta — a complete resume point the
        peer-replication plane can rebuild a DIFFERENT process from
        bitwise (the replayed trainer must continue the same rng
        stream the lost one would have)."""
        import numpy as np

        return HostSnapshot.take(
            state, strategy=self._result.strategy.to_json()
            if self._result else "",
            rng=[int(x) for x in np.asarray(self._rng).reshape(-1)],
            host_step=int(self._host_step),
        )

    def live_reshard(self, state: Any, devices=None,
                     snapshot: Optional[HostSnapshot] = None,
                     reason: str = "", emit_events: bool = True) -> Any:
        """The live recovery fast path: absorb a world change WITHOUT
        leaving the process.

        snapshot (host DRAM) → rebuild (program cache, often zero
        recompiles) → reshard (``device_put`` against the new
        shardings) → resume. Callers (the executor) drain their
        in-flight window first so the snapshot covers the last
        completed optimizer step. ``devices``: the surviving device
        subset (default: the full post-change ``jax.devices()`` world —
        an explicit construction-time subset is dropped, because after
        a membership change those handles may be stale/dead).
        ``snapshot``: a pre-taken HostSnapshot (e.g. from a caller that
        snapshotted before re-rendezvous); default is to take one now.

        The global batch stays fixed: ``Strategy.adjust_to_world``
        shrinks the data axis and grows grad accumulation to compensate
        — the reference's ``_set_gradient_accumulation_steps``
        semantics.
        """
        from dlrover_tpu.diagnosis.hang_detector import announce_long_phase

        announce_long_phase(900.0)  # rebuild window: not a hang
        old_result = self._result
        old_n = (
            old_result.mesh.devices.size if old_result is not None else 0
        )
        t0 = time.monotonic()
        if emit_events:
            emit_event(EventKind.LIVE_RESHARD_BEGIN, world_from=old_n,
                       reason=reason, step=int(self._host_step))
        with span(SpanName.LIVE_RESHARD, world_from=old_n):
            if snapshot is None:
                snapshot = self.snapshot(state)
            self._devices = list(devices) if devices is not None else None
            n = len(self._devices) if self._devices else len(jax.devices())
            compiles_before = self.compile_count
            self._result = self._build(self._devices)
            state = snapshot.restore(self._result.state_sharding)
            # the reshard program must have RUN before we claim
            # recovered (and before the timing below means anything)
            jax.block_until_ready(state)
        reshard_s = time.monotonic() - t0
        reg = get_registry()
        reg.counter(
            tm.LIVE_RESHARDS,
            help="world changes absorbed in-process (no restart)").inc()
        reg.histogram(
            tm.LIVE_RESHARD_TIME,
            help="snapshot -> rebuild -> reshard wall seconds",
        ).observe(reshard_s)
        recompiled = self.compile_count - compiles_before
        old_accum = (
            old_result.strategy.grad_accum_steps if old_result else 1
        )
        logger.info(
            "live reshard: %d -> %d devices in %.2fs (grad_accum "
            "%d -> %d, %s)", old_n, n, reshard_s, old_accum,
            self._result.strategy.grad_accum_steps,
            "program cache hit" if not recompiled else "recompiled",
        )
        if emit_events:
            emit_event(EventKind.LIVE_RESHARD_DONE, world_from=old_n,
                       world_to=n, reshard_seconds=round(reshard_s, 3),
                       recompiled=recompiled, step=snapshot.step)
        return state

    def prewarm(self, devices=None, execute: bool = True,
                steps_per_call: Optional[int] = None,
                mesh=None, dispatch_chunks: Optional[int] = None,
                moe_precision: Optional[str] = None,
                fsdp_precision: Optional[str] = None) -> bool:
        """Standby-compile the program for a topology OR knob set we may
        swap to — the (N - node_unit)-device survivor world before a
        failure, or an optimizer-chosen (``steps_per_call``, mesh
        override) before the retune that applies it — so the live
        reshard/retune that follows hits the program cache and pays
        zero recompiles. Returns True when a compile happened, False on
        a cache hit. Does NOT switch the trainer's active program,
        device set, or knobs (the temporary knob swap is restored).

        ``execute`` (default): run one throwaway step on the standby
        program — jit is lazy, so merely building the program object
        would still leave trace + XLA compile to the first post-swap
        step. The dummy step costs a transient extra copy of the state
        on the standby submesh; pass ``execute=False`` on models too
        large to double-book (the swap then pays the compile, but
        still skips the strategy/mesh rebuild)."""
        from dlrover_tpu.common.config import get_context

        prev_k, prev_mesh = self.steps_per_call, self._mesh_override
        prev_c = self.dispatch_chunks
        prev_p = self.moe_precision
        prev_fp = self.fsdp_precision
        prev_key = self._current_program_key
        if steps_per_call is not None:
            self.steps_per_call = max(1, int(steps_per_call))
        if mesh is not None:
            self._mesh_override = mesh
        if dispatch_chunks is not None:
            self.dispatch_chunks = max(1, int(dispatch_chunks))
        if moe_precision is not None:
            self.moe_precision = self._effective_precision(moe_precision)
        if fsdp_precision is not None:
            self.fsdp_precision = self._effective_precision(fsdp_precision)
        try:
            before = self.compile_count
            result = self._build(
                list(devices) if devices is not None else None)
            compiled = self.compile_count > before
            if execute and compiled:
                # the dummy step also forces the standby TRACE, which
                # is when ops.moe / models.llama read the chunk and
                # precision knobs off the Context
                self._execute_dummy_step(result)
        finally:
            self.steps_per_call = prev_k
            self._mesh_override = prev_mesh
            self.dispatch_chunks = prev_c
            self.moe_precision = prev_p
            self.fsdp_precision = prev_fp
            # the ACTIVE program keeps its trace-time knobs (and its
            # attribution identity — not re-pointed at the standby key)
            get_context().dispatch_chunks = prev_c
            get_context().moe_precision = prev_p
            get_context().fsdp_precision = prev_fp
            self._current_program_key = prev_key
        return compiled

    def _execute_dummy_step(self, result: AccelerateResult) -> None:
        """Force the lazy jit through trace + XLA compile by running one
        throwaway step on the standby program — the MULTI-step scan when
        that is what the knobs will dispatch."""
        from dlrover_tpu.diagnosis.hang_detector import (
            announce_long_phase,
        )

        announce_long_phase(900.0)  # standby compile: not a hang
        import jax.numpy as jnp

        rng = jax.random.PRNGKey(0)
        dummy = result.init_fn(rng)
        k = max(1, self.steps_per_call)
        if k > 1 and result.train_step_multi is not None:
            from dlrover_tpu.trainer.data import stack_batches

            stacked = stack_batches([self._example_batch] * k)
            sharded = result.shard_batch(stacked, stacked=True)
            rngs = jnp.stack([rng] * k)
            dummy, _unused = result.train_step_multi(
                dummy, sharded, rngs)
        else:
            sharded = result.shard_batch(self._example_batch)
            dummy, _unused = result.train_step(dummy, sharded, rng)
        jax.block_until_ready(dummy)
        logger.info(
            "prewarmed standby program (%d devices, K=%d): one dummy "
            "step executed", result.mesh.devices.size, k,
        )

    def retune(self, state: Any, steps_per_call: Optional[int] = None,
               mesh=None, dispatch_chunks: Optional[int] = None,
               moe_precision: Optional[str] = None,
               fsdp_precision: Optional[str] = None,
               reason: str = "optimizer") -> Any:
        """Apply optimizer-chosen PROGRAM knobs on the current world
        without a restart: ``steps_per_call`` (the lax.scan multi-step
        degree), ``dispatch_chunks`` / ``moe_precision`` /
        ``fsdp_precision`` (the grouped_ep chunked-dispatch degree and
        the MoE / dense-FSDP wire precisions — trace-time knobs the
        program-cache key carries) and/or a mesh override (a different
        factorization of the same devices). Same mechanics as
        ``live_reshard`` — the caller drains its window first;
        snapshot → rebuild → reshard — but against the unchanged
        device set, and through the program cache keyed on these very
        knobs, so a prewarmed knob set swaps with ZERO recompiles.
        (``grad_precision`` is deliberately absent: the error-feedback
        residual is part of TrainState, so that knob cannot flip under
        a live state.) On failure the previous knobs (and the
        previously compiled program) are restored and the error
        propagates — the job keeps running the old config."""
        prev_k, prev_mesh = self.steps_per_call, self._mesh_override
        prev_c = self.dispatch_chunks
        prev_p = self.moe_precision
        prev_fp = self.fsdp_precision
        if steps_per_call is not None:
            self.steps_per_call = max(1, int(steps_per_call))
        if mesh is not None:
            self._mesh_override = mesh
        if dispatch_chunks is not None:
            self.dispatch_chunks = max(1, int(dispatch_chunks))
        if moe_precision is not None:
            self.moe_precision = self._effective_precision(moe_precision)
        if fsdp_precision is not None:
            self.fsdp_precision = self._effective_precision(fsdp_precision)
        try:
            return self.live_reshard(
                state, devices=self._devices, reason=reason,
                emit_events=False,
            )
        except Exception:
            self.steps_per_call = prev_k
            self._mesh_override = prev_mesh
            self.dispatch_chunks = prev_c
            self.moe_precision = prev_p
            self.fsdp_precision = prev_fp
            # re-point at the old program (cache hit, and the Context
            # chunk knob re-pinned by _build) so the trainer stays
            # runnable with the pre-retune config
            self._result = self._build(self._devices)
            raise

    def on_world_change(self, state: Any, devices=None) -> Any:
        """The process-restart rebuild entrypoint (agent/bootstrap,
        after ``jax.distributed`` re-init; also the executor's classic
        ``request_restart`` path). Same mechanics as ``live_reshard``
        but WITHOUT the live-reshard timeline events: a restart-path
        rebuild must pair with the restart scenarios in the MTTR
        derivation, not inflate the ``live_reshard`` one."""
        return self.live_reshard(state, devices=devices,
                                 reason="on_world_change",
                                 emit_events=False)

    # -- hot loop ------------------------------------------------------------

    def step(self, state: Any, batch: Any) -> Tuple[Any, Dict]:
        self._rng, step_rng = jax.random.split(self._rng)
        sharded = self._result.shard_batch(batch)
        state, metrics = self._result.train_step(state, sharded, step_rng)
        self._host_step += 1
        step = self._host_step
        if self._master_client is not None and step % self._report_every == 0:
            try:
                from dlrover_tpu.common import comm

                self._master_client.report(
                    comm.GlobalStep(step=step, timestamp=time.time())
                )
                self._c_reports.inc()
            except Exception:  # noqa: BLE001 - reporting must never kill training
                self._c_report_failures.inc()
        if self._ckpt is not None and self._ckpt.interval.should_save(step):
            # never checkpoint a NaN-poisoned state: it would corrupt the
            # rollback/restore target (the one device sync this costs
            # happens only on save steps)
            if "finite" not in metrics or bool(metrics["finite"]):
                self.save(state)
            else:
                logger.warning(
                    "skipping checkpoint at step %d: non-finite state", step
                )
        return state, metrics

    def step_multi(self, state: Any, batches: Any) -> Tuple[Any, Dict]:
        """Dispatch ``steps_per_call`` optimizer steps as ONE compiled
        call (the ``lax.scan`` multi-step of ``accelerate``).

        ``batches``: a sequence of exactly ``steps_per_call`` host
        batches, or a pytree already stacked along a leading K axis
        (e.g. from ``DevicePreloader(steps_per_call=K)``). The rng
        stream advances by one split per optimizer step — identical to
        K calls of ``step`` — so a multi-step run is bit-identical to
        the synchronous loop on the same batch stream. Metrics return
        stacked ``[K, ...]`` leaves.
        """
        k = self.steps_per_call
        multi = self._result.train_step_multi
        if multi is None or k <= 1:
            raise RuntimeError(
                "step_multi needs steps_per_call > 1 at construction "
                f"(got steps_per_call={k})"
            )
        if isinstance(batches, (list, tuple)):
            if len(batches) != k:
                raise ValueError(
                    f"step_multi takes exactly steps_per_call={k} "
                    f"batches, got {len(batches)}"
                )
            from dlrover_tpu.trainer.data import stack_batches

            batches = stack_batches(list(batches))
        import jax.numpy as jnp

        rngs = []
        for _ in range(k):
            self._rng, r = jax.random.split(self._rng)
            rngs.append(r)
        sharded = self._result.shard_batch(batches, stacked=True)
        state, metrics = multi(state, sharded, jnp.stack(rngs))
        prev = self._host_step
        self._host_step += k
        step = self._host_step
        if self._master_client is not None and (
            step // self._report_every > prev // self._report_every
        ):
            try:
                from dlrover_tpu.common import comm

                self._master_client.report(
                    comm.GlobalStep(step=step, timestamp=time.time())
                )
                self._c_reports.inc()
            except Exception:  # noqa: BLE001 - reporting must never kill training
                self._c_report_failures.inc()
                logger.debug("global-step report failed", exc_info=True)
        if self._ckpt is not None and self._ckpt.interval.should_save(step):
            # the finite guard reads the stacked flags — one device sync,
            # only on save steps, covering every step in the group
            finite = metrics.get("finite")
            if finite is None or bool(jnp.all(finite)):
                self.save(state)
            else:
                logger.warning(
                    "skipping checkpoint at step %d: non-finite state "
                    "inside the %d-step group", step, k,
                )
        return state, metrics

    # -- checkpoint ----------------------------------------------------------

    def latest_checkpoint_step(self) -> Optional[int]:
        """Newest restorable step, flushing any in-flight async save
        first; None when no checkpointing is configured or nothing has
        been committed yet (the executor's rollback precondition)."""
        if self._ckpt is None:
            return None
        try:
            self._ckpt.wait()
        except Exception:  # noqa: BLE001
            logger.exception("flushing async checkpoint failed")
        return self._ckpt.latest_step()

    def save(self, state: Any, force: bool = True):
        if self._ckpt is None:
            return
        shard_ckpt = ""
        if self._master_client is not None:
            try:
                from dlrover_tpu.common import comm

                resp = self._master_client.get(
                    comm.ShardCheckpointRequest(dataset_name="")
                )
                shard_ckpt = getattr(resp, "content", "") or ""
            except Exception:  # noqa: BLE001
                pass
        self._ckpt.save(
            int(state.step),
            state,
            metadata={"strategy": self._result.strategy.to_json()},
            shard_checkpoint=shard_ckpt,
            force=force,
        )

    def finalize(self) -> bool:
        """Flush + close checkpointing. Returns True when a staging
        mirror timed out (``ElasticCheckpointManager.wait``) — surfaced
        so exit paths (preemption drain) can report that the host-DRAM
        mirror never committed."""
        timed_out = False
        if self._ckpt is not None:
            timed_out = bool(self._ckpt.wait())
            self._ckpt.close()
        return timed_out
