"""Failover client: cluster-version handshake + change watcher.

Role parity: ``dlrover/trainer/tensorflow/failover/failover_client.py:21``
(local/global/restored cluster versions negotiated through the master's
ElasticPsService) and ``tensorflow_failover.py:33-144``
(``TensorflowFailover`` — a watcher thread that detects PS-cluster /
world changes and triggers a training-session restart).

On TPU the "session restart" is ``ElasticTrainer.on_world_change`` —
recompile for the new mesh and reshard state — so the watcher's job is
only detection + callback.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.names import EventKind

logger = get_logger("trainer.failover")


class VersionType:
    LOCAL = "local"
    GLOBAL = "global"
    RESTORED = "restored"


class RecoveryDecision:
    """The three rungs of the recovery ladder (docs/operations.md),
    cheapest first. Each rung strictly contains the next's cost: a live
    reshard is a drain + snapshot + (often cached) rebuild; a process
    restart adds boot + warm compile + staged restore; a pod restart
    adds scheduling + image pull + cold everything."""

    LIVE_RESHARD = "live_reshard"
    PROCESS_RESTART = "process_restart"
    POD_RESTART = "pod_restart"


# event kinds a *surviving* process can absorb by resharding in place:
# the world changed around it, but its own step loop, devices, and
# compiled programs are intact
_SURVIVABLE_KINDS = frozenset({
    EventKind.SCALE_PLAN_APPLIED,   # planned scale up/down
    EventKind.WORKER_FAILED,        # a PEER's worker died
    EventKind.PREEMPT_NOTICE,       # a PEER node is being preempted
    EventKind.RDZV_JOIN,            # nodes waiting to (re)join
})


def classify_recovery(
    event_kind: str,
    self_affected: bool = False,
    host_healthy: bool = True,
    world_viable: bool = True,
    mttr_table: Optional[Dict[str, float]] = None,
) -> str:
    """Pick the cheapest recovery rung that is actually safe.

    ``event_kind``: the triggering EventKind. ``self_affected``: the
    failure is on THIS node (own worker death, own preemption notice,
    own devices wedged) — an in-process reshard cannot help a process
    that is itself the casualty. ``host_healthy``: the node's
    host/accelerator diagnosis; False escalates past process restart
    (a restarted process on a sick host just fails again).
    ``world_viable``: the post-event world still satisfies min_nodes /
    node_unit (the master's rendezvous constraints) — without a viable
    survivor world there is nothing to reshard onto.

    ``mttr_table``: the master's predicted-MTTR-per-rung prices (the
    readiness auditor's calibrated ladder, attached to recovery plans).
    When present, the safety-admissible default of LIVE_RESHARD is
    additionally PRICED: if a restart-class rung (peer_rebuild /
    storage_restore) predicts strictly cheaper than the live reshard —
    e.g. a huge mesh whose drain + recompile dwarfs a tiny peer fetch —
    the decision takes the cheaper rung. Absent or unpriced tables keep
    today's ladder order, so the pricing can only ever move a decision
    on evidence.
    """
    if not host_healthy:
        return RecoveryDecision.POD_RESTART
    if self_affected:
        return RecoveryDecision.PROCESS_RESTART
    if event_kind in _SURVIVABLE_KINDS and world_viable:
        if mttr_table:
            from dlrover_tpu.telemetry.readiness import (
                RUNG_LIVE_RESHARD,
                RUNG_PEER_REBUILD,
                RUNG_STORAGE_RESTORE,
            )

            live = mttr_table.get(RUNG_LIVE_RESHARD)
            restart_prices = [
                mttr_table[r]
                for r in (RUNG_PEER_REBUILD, RUNG_STORAGE_RESTORE)
                if mttr_table.get(r) is not None
            ]
            if (live is not None and restart_prices
                    and min(restart_prices) < float(live)):
                return RecoveryDecision.PROCESS_RESTART
        return RecoveryDecision.LIVE_RESHARD
    return RecoveryDecision.PROCESS_RESTART


class FailoverClient:
    """Version handshake (reference failover_client.py): each worker
    keeps a LOCAL version; the master keeps GLOBAL (current cluster) and
    RESTORED (checkpoint the cluster came back from) versions. A worker
    whose LOCAL version trails GLOBAL must rebuild its session."""

    def __init__(self, master_client, task_type: str = "worker",
                 task_id: int = 0):
        self._client = master_client
        self._task_type = task_type
        self._task_id = task_id

    def init_version(self):
        """On startup: local <- global (first worker bumps global 0->1
        via a master-side compare-and-set, so two workers starting at
        once cannot both apply their own read-modify-write)."""
        global_version = self.get_version(VersionType.GLOBAL)
        if global_version == 0:
            self._client.update_cluster_version(
                VersionType.GLOBAL, 1, self._task_type, self._task_id,
                expected=0,
            )
            global_version = self.get_version(VersionType.GLOBAL)
        self.set_version(VersionType.LOCAL, global_version)

    def get_version(self, version_type: str) -> int:
        return self._client.get_cluster_version(
            version_type, self._task_type, self._task_id
        )

    def set_version(self, version_type: str, version: int):
        self._client.update_cluster_version(
            version_type, version, self._task_type, self._task_id
        )

    def ps_cluster_changed(self) -> bool:
        local = self.get_version(VersionType.LOCAL)
        global_v = self.get_version(VersionType.GLOBAL)
        return local < global_v

    def sync_to_global(self):
        self.set_version(
            VersionType.LOCAL, self.get_version(VersionType.GLOBAL)
        )


class TrainingFailover:
    """Watches for membership / PS-cluster changes and fires a restart
    callback (reference TensorflowFailover.start_failover_monitor)."""

    def __init__(
        self,
        master_client,
        on_change: Callable[[], None],
        failover_client: Optional[FailoverClient] = None,
        poll_interval: float = 5.0,
        on_reshard: Optional[Callable[[], None]] = None,
        mttr_table_fn: Optional[Callable[[], Dict[str, float]]] = None,
    ):
        self._client = master_client
        self._on_change = on_change
        # supplies the master's predicted-MTTR ladder at decision time
        # (None = unpriced: classify by safety ladder order alone)
        self._mttr_table_fn = mttr_table_fn
        # the live fast path: survivable membership changes (nodes
        # waiting at the rendezvous while this process is healthy) go
        # here instead of on_change, so the executor reshards in place.
        # PS-cluster changes always take on_change — a PS session
        # rebuild is not an SPMD reshard.
        self._on_reshard = on_reshard
        self._failover = failover_client
        self._interval = poll_interval
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._last_ps_addrs: Optional[List[str]] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="failover-monitor", daemon=True
        )
        self._thread.start()

    def _changed(self) -> str:
        """What changed: "" = nothing; "ps" = PS cluster (session
        rebuild); "rdzv" = SPMD membership (reshardable)."""
        # PS strategy: version handshake
        if self._failover is not None and self._failover.ps_cluster_changed():
            return "ps"
        # PS address list drift (reference: address_changed via TF_CONFIG)
        try:
            ps_nodes = self._client.query_ps_nodes()
            addrs = sorted(
                getattr(node, "service_addr", "") for node in ps_nodes.nodes
            )
            if self._last_ps_addrs is not None and addrs != self._last_ps_addrs:
                self._last_ps_addrs = addrs
                return "ps"
            self._last_ps_addrs = addrs
        except Exception as e:  # noqa: BLE001 — master briefly unreachable
            # tolerated (the next poll retries) but never silent: a
            # permanently failing query here means the watcher is blind
            # to PS membership changes (DLR002)
            logger.warning("query_ps_nodes failed, skipping PS-drift "
                           "check this poll (%s: %s)", type(e).__name__, e)
        # SPMD strategy: nodes waiting at the rendezvous
        try:
            if self._client.num_nodes_waiting() > 0:
                return "rdzv"
        except Exception as e:  # noqa: BLE001 — master briefly unreachable
            logger.warning("num_nodes_waiting failed, skipping rendezvous "
                           "check this poll (%s: %s)", type(e).__name__, e)
        return ""

    def _run(self):
        while not self._stopped.wait(self._interval):
            try:
                what = self._changed()
                if what:
                    if self._failover is not None:
                        self._failover.sync_to_global()
                    table = None
                    if what == "rdzv" and self._mttr_table_fn is not None:
                        try:
                            table = self._mttr_table_fn()
                        except Exception:  # noqa: BLE001 — stay unpriced
                            logger.warning(
                                "mttr table lookup failed; classifying "
                                "unpriced", exc_info=True)
                            table = None
                    decision = (
                        classify_recovery(
                            EventKind.RDZV_JOIN, mttr_table=table)
                        if what == "rdzv"
                        else RecoveryDecision.PROCESS_RESTART
                    )
                    if (
                        decision == RecoveryDecision.LIVE_RESHARD
                        and self._on_reshard is not None
                    ):
                        logger.info("membership change detected; firing "
                                    "live reshard (survivable)")
                        self._on_reshard()
                    else:
                        logger.info(
                            "membership change detected; firing restart")
                        self._on_change()
            except Exception:  # noqa: BLE001
                logger.exception("failover monitor iteration failed")

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 1)
