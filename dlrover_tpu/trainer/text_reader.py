"""Text-file readers fed by the dynamic shard service.

Role parity: ``dlrover/trainer/tensorflow/reader/file_reader.py`` (an
ElasticReader exposing ``count_data`` + ``read_data_by_index_range``, fed
shard index ranges by the sharding client) — re-designed for the jax
training loop: the reader maps *record indices* to fixed-shape token
batches, so the master stays on the per-shard path and the device sees
static shapes only.

- ``LineIndexedFile``: one pass builds a byte-offset index; thereafter any
  index range is a seek+read, so workers can consume shards in any order
  (dynamic sharding's whole point: fast workers get more shards).
- ``ByteTokenizer``: zero-dependency byte-level tokenizer (vocab 256 +
  pad/bos), fixed ``seq_len`` per record — honest tokenization for tests
  and examples without shipping a vocab file; swap in any callable with
  the same signature for real vocabularies.
- ``ShardedTextBatches``: glues a ShardingClient to the reader — fetch
  shard, render [B, S] batches, report batch/task completion. Shard
  checkpoint/restore comes for free from the master.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger("trainer.text")


class LineIndexedFile:
    """Random access to a text file by line index."""

    def __init__(self, path: str):
        self.path = path
        # start offset of each line; Python's line iteration never yields
        # a phantom record for a trailing newline
        self._starts: List[int] = []
        offset = 0
        with open(path, "rb") as f:
            for line in f:
                self._starts.append(offset)
                offset += len(line)
        self._size = offset

    def count(self) -> int:
        """Number of records (reference: ``FileReader.count_data``)."""
        return len(self._starts)

    def _read_range_into(self, f, start: int, end: int,
                         out: List[bytes]) -> None:
        f.seek(self._starts[start])
        for i in range(start, end):
            upper = (self._starts[i + 1] if i + 1 < self.count()
                     else self._size)
            raw = f.read(upper - self._starts[i])
            out.append(raw.rstrip(b"\r\n"))

    def read_range(self, start: int, end: int) -> List[bytes]:
        """Records in [start, end) (reference:
        ``read_data_by_index_range``)."""
        end = min(end, self.count())
        if start >= end:
            return []
        out: List[bytes] = []
        with open(self.path, "rb") as f:
            self._read_range_into(f, start, end, out)
        return out

    def read_indices(self, indices: List[int]) -> List[bytes]:
        """Records at arbitrary indices, in the given order (shuffled
        shards carry an explicit permutation). One open for the whole
        call; contiguous runs share one seek — a fully shuffled shard is
        seeks, not open/close pairs (which dominate on network fs)."""
        out: List[bytes] = []
        dropped = 0
        with open(self.path, "rb") as f:
            i = 0
            while i < len(indices):
                j = i
                while j + 1 < len(indices) and \
                        indices[j + 1] == indices[j] + 1:
                    j += 1
                if indices[i] < self.count():
                    upper = min(indices[j] + 1, self.count())
                    dropped += indices[j] + 1 - upper
                    self._read_range_into(f, indices[i], upper, out)
                else:
                    dropped += j + 1 - i
                i = j + 1
        if dropped:
            # the sharding protocol still credits these records as
            # consumed (report_batch_done counts batch_size regardless),
            # so a master/reader dataset_size mismatch would otherwise
            # shrink the epoch with no signal at all
            logger.warning(
                "%s: dropped %d out-of-range record indices (max index "
                "%d >= %d records) — the master's dataset_size "
                "over-declares this file",
                self.path, dropped, max(indices), self.count(),
            )
        return out


class ByteTokenizer:
    """Byte-level ids in [2, 257]; 0 = pad, 1 = bos. Fixed length."""

    vocab_size = 258

    def __init__(self, seq_len: int):
        self.seq_len = seq_len

    def __call__(self, record: bytes) -> np.ndarray:
        ids = np.frombuffer(record[: self.seq_len - 1], np.uint8)
        out = np.zeros((self.seq_len,), np.int32)
        out[0] = 1  # bos
        out[1:1 + len(ids)] = ids.astype(np.int32) + 2
        return out

    def encode(self, record: bytes) -> np.ndarray:
        """Variable-length encoding (bos + bytes), for the packing path."""
        ids = np.frombuffer(record, np.uint8).astype(np.int32) + 2
        return np.concatenate([np.asarray([1], np.int32), ids])


class HFTokenizerAdapter:
    """Plug a HuggingFace tokenizer (``transformers`` PreTrained* or a
    raw ``tokenizers.Tokenizer``) into the shard-fed batch source: maps
    its encode onto the fixed-shape ``__call__`` (padded mode) and the
    variable-length ``encode`` (packed mode) this pipeline expects.
    ``ByteTokenizer`` remains the zero-dependency default; this is the
    production-vocabulary path."""

    def __init__(self, tokenizer, seq_len: int,
                 pad_id: int = 0, bos_id: Optional[int] = None,
                 eos_id: Optional[int] = None):
        self._tok = tokenizer
        self.seq_len = seq_len
        self.pad_id = pad_id
        self.bos_id = bos_id
        # with eos_id set, every document gets a terminal EOS appended;
        # ``_render`` additionally knows (via this attribute) to keep the
        # end-of-text prediction target alive under the pad == eos
        # convention, where the terminal EOS is otherwise folded into
        # the trailing pad run by the position-based mask
        self.eos_id = eos_id
        size = getattr(tokenizer, "vocab_size", None)
        if size is None and hasattr(tokenizer, "get_vocab_size"):
            size = tokenizer.get_vocab_size()
        self.vocab_size = int(size)

    def _ids(self, record: bytes) -> List[int]:
        text = record.decode("utf-8", errors="replace")
        try:
            # transformers tokenizers inject their own specials by
            # default (duplicated BOS, [CLS]/[SEP] in every record) —
            # this pipeline owns special-token placement
            encoded = self._tok.encode(text, add_special_tokens=False)
        except TypeError:  # raw `tokenizers.Tokenizer`: no such kwarg
            encoded = self._tok.encode(text)
        ids = encoded if isinstance(encoded, list) else encoded.ids
        ids = list(ids)
        if self.bos_id is not None:
            ids = [self.bos_id] + ids
        if self.eos_id is not None:
            ids = ids + [self.eos_id]
        return ids

    def encode(self, record: bytes) -> np.ndarray:
        return np.asarray(self._ids(record), np.int32)

    def __call__(self, record: bytes) -> np.ndarray:
        ids = self._ids(record)[: self.seq_len]
        out = np.full((self.seq_len,), self.pad_id, np.int32)
        out[: len(ids)] = ids
        return out


class ShardedTextBatches:
    """Dynamic-shard consumption loop over a line-indexed text file.

    Yields ``{"input_ids": [B, S], "labels": [B, S]}`` numpy batches
    (labels = inputs shifted left, pad masked to -100). The master hands
    out index shards; batch rendering happens worker-side, so the master
    is never on the per-batch path.
    """

    def __init__(
        self,
        sharding_client,
        reader: LineIndexedFile,
        batch_size: int,
        tokenizer: Optional[Callable[[bytes], np.ndarray]] = None,
        seq_len: int = 128,
        pack: bool = False,
    ):
        self._client = sharding_client
        self._reader = reader
        self._batch = batch_size
        self._seq_len = seq_len
        self._tok = tokenizer or ByteTokenizer(seq_len)
        self._pack = pack
        if pack and not hasattr(self._tok, "encode"):
            raise ValueError(
                "pack=True needs a tokenizer with an .encode(bytes) -> "
                "variable-length id array method (fixed-length __call__ "
                "tokenizers cannot pack); ByteTokenizer provides one"
            )
        # packing state: documents spill across shard fetches
        self._pack_rows: List[dict] = []
        self._cur_ids: List[int] = []
        self._cur_segs: List[int] = []
        self._next_seg = 0
        self._rows_finished = 0  # rows ever completed by _finish_row
        self._rows_consumed = 0  # rows ever emitted in yielded batches
        # (task_id, row mark): the shard may be reported done only once
        # every row holding its tokens has been YIELDED — reporting at
        # pack time would let the master mark records consumed that a
        # worker crash would lose from the in-memory buffer
        self._pending_tasks: List[Tuple[int, int]] = []

    def _render(self, records: List[bytes]) -> dict:
        ids = np.stack([self._tok(r) for r in records])
        labels = np.full_like(ids, -100)
        labels[:, :-1] = ids[:, 1:]
        # mask pad by POSITION (the trailing pad run), not by token id —
        # masking every occurrence of the id would silently untrain real
        # tokens sharing it (the common pad == eos convention)
        pad_id = getattr(self._tok, "pad_id", 0)
        not_pad = ids != pad_id
        has_any = not_pad.any(axis=1)
        lengths = np.where(
            has_any, ids.shape[1] - np.argmax(not_pad[:, ::-1], axis=1), 0
        )
        eos_id = getattr(self._tok, "eos_id", None)
        if eos_id is not None and eos_id == pad_id:
            # pad == eos convention with a known eos: the document's
            # terminal EOS shares the pad id, so the position scan folds
            # it into the trailing pad run — count exactly one trailing
            # token as the real EOS so the model still gets an
            # end-of-text prediction target. (Tokenizers without an
            # eos_id keep the conservative mask: the terminal-EOS target
            # is the residual gap, documented here on purpose.)
            lengths = np.where(
                has_any & (lengths < ids.shape[1]), lengths + 1, lengths
            )
        # labels[t] predicts ids[t+1]: valid only while t+1 < length
        t = np.arange(ids.shape[1])[None, :]
        labels[t >= lengths[:, None] - 1] = -100
        return {"input_ids": ids, "labels": labels}

    # -- packed mode --------------------------------------------------------

    def _finish_row(self):
        s = self._seq_len
        ids = np.zeros((s,), np.int32)
        segs = np.full((s,), -1, np.int32)  # -1 = pad segment
        n = len(self._cur_ids)
        ids[:n] = self._cur_ids
        segs[:n] = self._cur_segs
        labels = np.full((s,), -100, np.int32)
        # next-token WITHIN a segment only: no target across document
        # boundaries or into pad
        labels[:-1] = ids[1:]
        boundary = segs[:-1] != segs[1:]
        labels[:-1][boundary] = -100
        labels[-1] = -100
        labels[segs == -1] = -100
        self._pack_rows.append(
            {"input_ids": ids, "segment_ids": segs, "labels": labels})
        self._cur_ids, self._cur_segs = [], []
        self._rows_finished += 1

    def _pack_records(self, records: List[bytes]):
        """Greedy fill: a document that doesn't fit the remainder is
        split; the continuation gets a fresh segment id (attention can't
        span rows, so the split IS a truncation boundary)."""
        s = self._seq_len
        for rec in records:
            encoded = self._tok.encode(rec)
            offset = 0
            while offset < len(encoded):
                room = s - len(self._cur_ids)
                if room == 0:
                    self._finish_row()
                    room = s
                take = encoded[offset:offset + room]
                seg = self._next_seg
                self._next_seg += 1
                self._cur_ids.extend(take.tolist())
                self._cur_segs.extend([seg] * len(take))
                offset += len(take)
            if len(self._cur_ids) == s:
                self._finish_row()

    def _drain_packed_batches(self, flush: bool = False):
        if flush and self._cur_ids:
            self._finish_row()
        while len(self._pack_rows) >= self._batch or (
            flush and self._pack_rows
        ):
            rows = self._pack_rows[: self._batch]
            del self._pack_rows[: len(rows)]
            self._rows_consumed += len(rows)
            while len(rows) < self._batch:
                # flush tail: repeat the last row for a static shape,
                # with labels masked — a packed row is a full dense
                # seq_len of tokens, so an unmasked copy would weight
                # its gradient batch-fill times
                filler = dict(rows[-1])
                filler["labels"] = np.full_like(filler["labels"], -100)
                rows.append(filler)
            yield {
                key: np.stack([r[key] for r in rows])
                for key in ("input_ids", "segment_ids", "labels")
            }
            # NB: no report_batch_done here. The master credits that rpc
            # in SOURCE RECORDS and auto-completes a shard when credits
            # reach its size (batch_dataset_manager.report_batch_done) —
            # packed rows are not records, so crediting them would pop
            # the task out of 'doing' while its tokens still sit in this
            # buffer, silently bypassing the deferred completion below.
            self._report_emitted_tasks()

    def _report_emitted_tasks(self, flush: bool = False):
        """Complete shards whose every row has been yielded (or all of
        them at flush, when the buffers are empty by construction)."""
        remaining = []
        for task_id, mark in self._pending_tasks:
            if flush or mark <= self._rows_consumed:
                self._client.report_task_done_by_id(task_id)
            else:
                remaining.append((task_id, mark))
        self._pending_tasks = remaining

    def __iter__(self) -> Iterator[dict]:
        while True:
            shard = self._client.fetch_shard()
            if shard is None:
                if self._pack:
                    yield from self._drain_packed_batches(flush=True)
                    self._report_emitted_tasks(flush=True)
                return
            if shard.record_indices:
                # shuffled datasets: the master's shard carries an
                # explicit permutation — honor it, or "shuffle=True"
                # would silently train on contiguous ranges
                records = self._reader.read_indices(
                    list(shard.record_indices))
            else:
                records = self._reader.read_range(shard.start, shard.end)
            if self._pack:
                self._pack_records(records)
                task_id = self._client.current_task_id
                if task_id is not None:
                    # completion deferred until this shard's rows (incl.
                    # the still-open partial row) have been YIELDED
                    mark = self._rows_finished + (
                        1 if self._cur_ids else 0)
                    self._pending_tasks.append((task_id, mark))
                yield from self._drain_packed_batches()
                continue
            for lo in range(0, len(records), self._batch):
                chunk = records[lo:lo + self._batch]
                n_real = len(chunk)
                if n_real < self._batch:
                    # pad the tail batch to a static shape (XLA: one
                    # compiled program) by repeating the last record —
                    # with the copies' labels masked, or the repeated
                    # record would train at (batch - n_real + 1)x weight
                    chunk = chunk + [chunk[-1]] * (self._batch - n_real)
                batch = self._render(chunk)
                if n_real < self._batch:
                    batch["labels"][n_real:] = -100
                yield batch
                self._client.report_batch_done()
            self._client.report_task_done()
