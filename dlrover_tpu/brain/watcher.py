"""Cluster watcher: platform state -> Brain datastore.

Role parity: ``dlrover/go/brain/pkg/platform/k8s/watcher`` (the
``k8smonitor`` command): a cluster-scoped monitor that ingests job and
node state into the Brain's datastore INDEPENDENT of job
self-reporting. Jobs that never wire up a ``BrainStatsReporter`` — or
die before their exit report — still leave the history that cold-starts
the next similar job's resource plan
(``optimize_job_worker_resource.go:30-120``).

Structure:
- ``ClusterSource`` is the minimal platform contract (list jobs, list a
  job's nodes with usage). ``K8sClusterSource`` adapts the operator's
  ``K8sClient`` (ElasticJob CRs + labeled pods); tests and other
  platforms (Ray, local) supply their own source.
- ``ClusterWatcher`` polls the source and persists the same
  ``MetricType`` rows the self-reporting path writes (JOB_META on first
  sight, RUNTIME_INFO per poll, JOB_EXIT_REASON once on completion), so
  every Brain algorithm consumes watcher-fed history transparently.
- The sink is anything with ``persist_metrics`` — a ``BaseDatastore``
  for an in-process Brain, a ``BrainClient`` for a remote one.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Protocol

from dlrover_tpu.brain.messages import BrainJobMetrics, MetricType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.scheduler.kubernetes import (
    parse_cpu_cores,
    parse_memory_mib,
)

logger = get_logger("brain.watcher")

# ElasticJob CR phases that mean "this job is finished"
_TERMINAL_PHASES = {"Succeeded", "Failed", "Completed"}


class ClusterSource(Protocol):
    """What the watcher needs from a platform."""

    def list_jobs(self) -> List[Dict]:
        """[{"name", "uid", "phase", "user"?, "node_unit"?}, ...]"""
        ...

    def list_job_nodes(self, job_name: str) -> Dict[str, List[Dict]]:
        """{node_type: [{"name", "cpu", "used_cpu", "memory",
        "used_memory"}, ...]} — requests plus observed usage."""
        ...


class K8sClusterSource:
    """Adapt the operator's ``K8sClient`` to the watcher contract.

    Jobs come from ElasticJob custom resources; nodes from pods labeled
    ``elasticjob-name``. Usage comes from the client's ``pod_metrics``
    method when the cluster runs a metrics server (optional — requests
    are still recorded without it, which is enough for the count/shape
    dimensions of the planning algorithms).
    """

    def __init__(self, client):
        self._client = client

    def list_jobs(self) -> List[Dict]:
        from dlrover_tpu.scheduler.kubernetes import ELASTICJOB_PLURAL

        jobs = []
        for cr in self._client.list_custom_resources(
            ELASTICJOB_PLURAL
        ) or []:
            meta = cr.get("metadata", {})
            jobs.append({
                "name": meta.get("name", ""),
                "uid": meta.get("uid", ""),
                "phase": cr.get("status", {}).get("phase", ""),
                "user": meta.get("labels", {}).get("user", ""),
                "node_unit": int(
                    cr.get("spec", {}).get("nodeUnit", 1) or 1
                ),
            })
        return jobs

    def list_job_nodes(self, job_name: str) -> Dict[str, List[Dict]]:
        pods = self._client.list_pods(
            label_selector=f"elasticjob-name={job_name}"
        ) or []
        usage = {}
        pod_metrics = getattr(self._client, "pod_metrics", None)
        if pod_metrics is not None:
            try:
                usage = pod_metrics(job_name) or {}
            except Exception:  # noqa: BLE001 — metrics server optional
                usage = {}
        nodes: Dict[str, List[Dict]] = {}
        for pod in pods:
            meta = pod.get("metadata", {})
            labels = meta.get("labels", {}) or {}
            name = meta.get("name", "")
            # the labels OUR operator/scaler actually write
            # (scheduler.kubernetes.build_pod_labels: "replica-type";
            # controller.build_master_pod: "elasticjob-role: master")
            node_type = labels.get("replica-type") or labels.get(
                "node-type", "worker"
            )
            if (node_type == "master"
                    or labels.get("elasticjob-role") == "master"):
                continue
            # the pod's effective request is the SUM across containers
            # (sidecars included — k8s schedules on the sum)
            cpu, mem = 0.0, 0
            for c in pod.get("spec", {}).get("containers", []):
                req = c.get("resources", {}).get("requests", {})
                cpu += parse_cpu_cores(req.get("cpu", 0))
                mem += parse_memory_mib(req.get("memory", 0))
            used = usage.get(name, {})
            nodes.setdefault(node_type, []).append({
                "name": name,
                "cpu": cpu,
                "memory": mem,
                "used_cpu": float(used.get("cpu", 0)),
                "used_memory": int(used.get("memory", 0)),
            })
        return nodes


class ClusterWatcher:
    """Poll a ``ClusterSource`` and feed the Brain.

    Dedup state (which jobs have META / EXIT rows) is rebuilt from the
    sink when it is a datastore, so a restarted watcher over a durable
    sqlite store does not duplicate one-shot rows.
    """

    def __init__(self, sink, source: ClusterSource,
                 interval: float = 30.0):
        self._sink = sink
        self._source = source
        self._interval = interval
        self._seen_meta = set()
        self._seen_exit = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # rebuild dedup state from a durable datastore sink
        lister = getattr(sink, "list_job_uuids", None)
        latest = getattr(sink, "latest", None)
        if lister is not None and latest is not None:
            try:
                for uuid in lister():
                    if latest(uuid, MetricType.JOB_META) is not None:
                        self._seen_meta.add(uuid)
                    if latest(
                        uuid, MetricType.JOB_EXIT_REASON
                    ) is not None:
                        self._seen_exit.add(uuid)
            except Exception:  # noqa: BLE001 — dedup is best-effort
                pass

    def _persist(self, uuid: str, name: str, metric_type: str,
                 payload: Dict):
        self._sink.persist_metrics(BrainJobMetrics(
            job_uuid=uuid, job_name=name, metric_type=metric_type,
            payload=payload, timestamp=time.time(),
        ))

    def poll_once(self) -> int:
        """One sweep; returns the number of jobs observed."""
        try:
            jobs = self._source.list_jobs()
        except Exception as e:  # noqa: BLE001 — platform hiccups
            logger.warning("cluster source list_jobs failed: %s", e)
            return 0
        for job in jobs:
            name = job.get("name", "")
            uuid = job.get("uid") or name
            if not name:
                continue
            if uuid not in self._seen_meta:
                self._persist(uuid, name, MetricType.JOB_META, {
                    "name": name,
                    "user": job.get("user", ""),
                    "node_unit": job.get("node_unit", 1),
                    "observed_by": "cluster_watcher",
                })
                self._seen_meta.add(uuid)
            phase = job.get("phase", "")
            if phase in _TERMINAL_PHASES:
                if uuid not in self._seen_exit:
                    self._persist(
                        uuid, name, MetricType.JOB_EXIT_REASON,
                        {"reason": phase,
                         "observed_by": "cluster_watcher"},
                    )
                    self._seen_exit.add(uuid)
                continue  # no runtime sample for a finished job
            try:
                nodes = self._source.list_job_nodes(name)
            except Exception as e:  # noqa: BLE001
                logger.warning("list_job_nodes(%s) failed: %s", name, e)
                continue
            workers = len(nodes.get("worker", []))
            # NO "speed" key: throughput is self-reported by the job; a
            # watcher row carrying speed=0.0 could shadow a genuine
            # sample for any consumer that reads only the latest row.
            # The watcher contributes topology + usage only.
            self._persist(uuid, name, MetricType.RUNTIME_INFO, {
                "workers": workers,
                "nodes": nodes,
                "observed_by": "cluster_watcher",
            })
        return len(jobs)

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="brain-cluster-watcher", daemon=True
        )
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("cluster watcher poll failed")
            self._stop.wait(self._interval)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
