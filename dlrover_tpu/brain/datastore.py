"""Brain datastores.

Role parity: ``dlrover/go/brain/pkg/datastore`` (MySQL-backed
``JobMetrics``/``JobNode`` tables, ``datastore/implementation/utils/
mysql.go``). The cluster store here is sqlite (stdlib, durable, zero
deps) behind the same interface as the in-memory store used in tests.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

from dlrover_tpu.brain.messages import BrainJobMetrics


class BaseDatastore(ABC):
    @abstractmethod
    def persist_metrics(self, m: BrainJobMetrics) -> None:
        ...

    @abstractmethod
    def get_job_metrics(
        self, job_uuid: str, metric_type: str = ""
    ) -> List[BrainJobMetrics]:
        ...

    @abstractmethod
    def list_job_uuids(self) -> List[str]:
        ...

    def latest(
        self, job_uuid: str, metric_type: str
    ) -> Optional[BrainJobMetrics]:
        rows = self.get_job_metrics(job_uuid, metric_type)
        return rows[-1] if rows else None


class MemoryDatastore(BaseDatastore):
    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[str, List[BrainJobMetrics]] = {}

    def persist_metrics(self, m: BrainJobMetrics) -> None:
        if not m.timestamp:
            m.timestamp = time.time()
        with self._lock:
            self._rows.setdefault(m.job_uuid, []).append(m)

    def get_job_metrics(self, job_uuid, metric_type=""):
        with self._lock:
            rows = list(self._rows.get(job_uuid, []))
        if metric_type:
            rows = [r for r in rows if r.metric_type == metric_type]
        return rows

    def list_job_uuids(self):
        with self._lock:
            return list(self._rows)


class SqliteDatastore(BaseDatastore):
    """Durable cluster store (the MySQL role). One connection per call —
    sqlite handles locking; throughput needs are control-plane scale."""

    def __init__(self, path: str):
        self._path = path
        with self._conn() as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS job_metrics ("
                "  job_uuid TEXT, job_name TEXT, metric_type TEXT,"
                "  payload TEXT, timestamp REAL)"
            )
            conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_job_metrics "
                "ON job_metrics (job_uuid, metric_type)"
            )

    def _conn(self):
        return sqlite3.connect(self._path, timeout=10.0)

    def persist_metrics(self, m: BrainJobMetrics) -> None:
        with self._conn() as conn:
            conn.execute(
                "INSERT INTO job_metrics VALUES (?, ?, ?, ?, ?)",
                (
                    m.job_uuid, m.job_name, m.metric_type,
                    json.dumps(m.payload), m.timestamp or time.time(),
                ),
            )

    def get_job_metrics(self, job_uuid, metric_type=""):
        sql = (
            "SELECT job_uuid, job_name, metric_type, payload, timestamp "
            "FROM job_metrics WHERE job_uuid = ?"
        )
        args: List = [job_uuid]
        if metric_type:
            sql += " AND metric_type = ?"
            args.append(metric_type)
        sql += " ORDER BY timestamp"
        with self._conn() as conn:
            rows = conn.execute(sql, args).fetchall()
        return [
            BrainJobMetrics(
                job_uuid=r[0], job_name=r[1], metric_type=r[2],
                payload=json.loads(r[3]), timestamp=r[4],
            )
            for r in rows
        ]

    def list_job_uuids(self):
        with self._conn() as conn:
            rows = conn.execute(
                "SELECT DISTINCT job_uuid FROM job_metrics"
            ).fetchall()
        return [r[0] for r in rows]


def new_datastore(spec: str) -> BaseDatastore:
    """"memory" or "sqlite:///path/to.db"."""
    if spec == "memory" or not spec:
        return MemoryDatastore()
    if spec.startswith("sqlite://"):
        return SqliteDatastore(spec[len("sqlite://"):])
    raise ValueError(f"unknown datastore spec {spec!r}")
