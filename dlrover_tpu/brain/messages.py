"""Brain wire messages.

Role parity: ``dlrover/proto/brain.proto`` (``JobMetrics``,
``OptimizeRequest``/``OptimizeResponse``, ``JobMetricsRequest`` — service
rpcs ``persist_metrics`` / ``optimize`` / ``get_job_metrics``,
``brain.proto:196-199``). JSON-framed dataclasses like the rest of the
control plane.
"""

from __future__ import annotations

from dataclasses import field
from typing import Dict, List

from dlrover_tpu.common import serialize


class MetricType:
    JOB_META = "job_meta"
    MODEL_FEATURE = "model_feature"
    RUNTIME_INFO = "runtime_info"
    TRAINING_HYPER_PARAMS = "training_hyper_params"
    JOB_EXIT_REASON = "job_exit_reason"
    RESOURCE_USAGE = "resource_usage"


@serialize.message
class BrainJobMetrics:
    """persist_metrics payload (reference ``JobMetrics``)."""

    job_uuid: str = ""
    job_name: str = ""
    metric_type: str = ""  # MetricType
    payload: Dict = field(default_factory=dict)
    timestamp: float = 0.0


@serialize.message
class OptimizeRequest:
    """optimize rpc (reference ``OptimizeRequest``: type + config +
    jobs). ``stage`` selects the algorithm via the brain config."""

    job_uuid: str = ""
    job_name: str = ""
    stage: str = ""  # JobStage
    algorithm: str = ""  # explicit override; else config decides by stage
    config: Dict = field(default_factory=dict)


@serialize.message
class GroupResourceMsg:
    count: int = 0
    cpu: float = 0.0
    memory: int = 0  # MiB
    chips: int = 0


@serialize.message
class OptimizePlanMsg:
    """optimize response (reference ``JobOptimizePlan``/``JobResource``)."""

    success: bool = True
    reason: str = ""
    # node_type -> group resource
    group_resources: Dict[str, GroupResourceMsg] = field(default_factory=dict)
    # node_name -> {"cpu", "memory"} for in-place migration
    node_resources: Dict[str, Dict] = field(default_factory=dict)


@serialize.message
class JobMetricsQuery:
    job_uuid: str = ""
    metric_type: str = ""  # optional filter


@serialize.message
class JobMetricsDump:
    job_uuid: str = ""
    metrics: List[BrainJobMetrics] = field(default_factory=list)
