"""Brain optimization algorithms.

Role parity: ``dlrover/go/brain/pkg/optimizer/implementation/
optalgorithm/*.go`` — eight algorithms keyed by name, selected through
the brain config. Each consumes the datastore's metric history (which —
unlike the per-job local optimizer — spans *all* jobs on the cluster,
enabling cold-start plans learned from similar completed jobs).

Payload conventions (``BrainJobMetrics.payload``):
  RUNTIME_INFO: {"speed": steps/s (OPTIONAL — present only on
                 self-reported rows; ClusterWatcher rows omit it, so
                 consumers must filter with .get("speed")),
                 "workers": n,
                 "nodes": {type: [{"name","cpu","used_cpu","memory",
                                   "used_memory"}]}}
  MODEL_FEATURE: {"param_count": n, "flops_per_step": f}
  JOB_META: {"name", "user", "strategy", "node_unit"}
  JOB_EXIT_REASON: {"reason", "node_type", "node_name"}
"""

from __future__ import annotations

import re
import statistics
from typing import Callable, Dict, List, Optional

from dlrover_tpu.brain.datastore import BaseDatastore
from dlrover_tpu.brain.messages import (
    GroupResourceMsg,
    MetricType,
    OptimizePlanMsg,
    OptimizeRequest,
)
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import get_logger

logger = get_logger("brain.algorithms")

_REGISTRY: Dict[str, Callable] = {}

_PS_COLD = GroupResourceMsg(count=1, cpu=8, memory=16384)
_WORKER_COLD = GroupResourceMsg(count=1, cpu=4, memory=8192)


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_algorithm(name: str) -> Optional[Callable]:
    return _REGISTRY.get(name)


def algorithm_names() -> List[str]:
    return sorted(_REGISTRY)


def _base_name(job_name: str) -> str:
    """Recurring jobs differ only by a numeric/date suffix."""
    return re.sub(r"[-_]\d+$", "", job_name)


def _similar_finished_jobs(
    store: BaseDatastore, job_name: str, limit: int = 5
) -> List[str]:
    base = _base_name(job_name)
    hits = []
    for uuid in store.list_job_uuids():
        meta = store.latest(uuid, MetricType.JOB_META)
        if meta is None:
            continue
        if _base_name(meta.payload.get("name", "")) != base:
            continue
        if store.latest(uuid, MetricType.JOB_EXIT_REASON) is None:
            continue  # still running
        hits.append(uuid)
    return hits[-limit:]


def _runtime_series(store: BaseDatastore, job_uuid: str) -> List[Dict]:
    return [
        m.payload
        for m in store.get_job_metrics(job_uuid, MetricType.RUNTIME_INFO)
    ]


def _plan(**groups) -> OptimizePlanMsg:
    return OptimizePlanMsg(group_resources=dict(groups))


# -- create-time (cold or history-informed) ---------------------------------


@register("optimize_job_ps_cold_create_resource")
def ps_cold_create(store, req: OptimizeRequest) -> OptimizePlanMsg:
    return _plan(**{NodeType.PS: _PS_COLD, NodeType.WORKER: _WORKER_COLD})


@register("optimize_job_ps_create_resource")
def ps_create(store, req: OptimizeRequest) -> OptimizePlanMsg:
    """Initial PS plan from the *peak observed* usage of similar jobs
    (``optimize_job_ps_create_resource.go``)."""
    similar = _similar_finished_jobs(store, req.job_name)
    if not similar:
        return ps_cold_create(store, req)
    counts, cpus, mems = [], [], []
    for uuid in similar:
        for sample in _runtime_series(store, uuid):
            ps_nodes = sample.get("nodes", {}).get(NodeType.PS, [])
            if not ps_nodes:
                continue
            counts.append(len(ps_nodes))
            cpus.append(max(n.get("used_cpu", 0) for n in ps_nodes))
            mems.append(max(n.get("used_memory", 0) for n in ps_nodes))
    if not counts:
        return ps_cold_create(store, req)
    plan = _plan(**{
        NodeType.PS: GroupResourceMsg(
            count=int(statistics.median(counts)),
            # headroom over the hottest observed PS
            cpu=max(1.0, 1.25 * max(cpus)),
            memory=max(1024, int(1.25 * max(mems))),
        ),
    })
    return plan


@register("optimize_job_worker_create_resource")
def worker_create(store, req: OptimizeRequest) -> OptimizePlanMsg:
    """Initial worker plan: the worker count similar jobs converged to."""
    similar = _similar_finished_jobs(store, req.job_name)
    finals = []
    for uuid in similar:
        series = _runtime_series(store, uuid)
        if series:
            finals.append(series[-1].get("workers", 0))
    finals = [f for f in finals if f > 0]
    if not finals:
        return _plan(**{NodeType.WORKER: _WORKER_COLD})
    return _plan(**{
        NodeType.WORKER: GroupResourceMsg(
            count=int(statistics.median(finals)),
            cpu=_WORKER_COLD.cpu, memory=_WORKER_COLD.memory,
        ),
    })


# -- runtime adjustment ------------------------------------------------------


@register("optimize_job_ps_init_adjust_resource")
def ps_init_adjust(store, req: OptimizeRequest) -> OptimizePlanMsg:
    """Re-size the PS group once model stats exist
    (``optimize_job_ps_init_adjust_resource.go``): 16 bytes/param across
    the group, bounded PS count."""
    model = store.latest(req.job_uuid, MetricType.MODEL_FEATURE)
    if model is None or model.payload.get("param_count", 0) <= 0:
        return OptimizePlanMsg(success=False, reason="no model feature yet")
    params = model.payload["param_count"]
    total_mb = int(params * 16 / (1024 * 1024)) + 2048
    count = max(1, min(8, total_mb // _PS_COLD.memory + 1))
    return _plan(**{
        NodeType.PS: GroupResourceMsg(
            count=count, cpu=_PS_COLD.cpu,
            memory=max(_PS_COLD.memory, total_mb // count),
        ),
    })


@register("optimize_job_worker_resource")
def worker_resource(store, req: OptimizeRequest) -> OptimizePlanMsg:
    """Runtime worker count from the speed trend and PS CPU headroom
    (``optimize_job_worker_resource.go:30-120``): keep adding workers
    while per-worker speed holds and the hottest PS stays under the
    utilization threshold."""
    series = _runtime_series(store, req.job_uuid)
    if len(series) < 4:
        return OptimizePlanMsg(success=False, reason="not enough samples")
    threshold = float(req.config.get("ps_cpu_threshold", 0.8))
    cur_workers = series[-1].get("workers", 0)
    if cur_workers <= 0:
        return OptimizePlanMsg(success=False, reason="no running workers")

    # hottest PS utilization over the recent window
    utils = []
    for sample in series[-8:]:
        for node in sample.get("nodes", {}).get(NodeType.PS, []):
            req_cpu = max(node.get("cpu", 0), 0.1)
            utils.append(node.get("used_cpu", 0) / req_cpu)
    ps_util = max(utils) if utils else 0.0
    if ps_util >= threshold:
        return OptimizePlanMsg(success=False, reason="ps saturated")

    # per-worker speed trend: only grow while efficiency holds
    half = len(series) // 2
    eff = lambda ss: statistics.mean(  # noqa: E731
        s["speed"] / max(s.get("workers", 1), 1)
        for s in ss if s.get("speed", 0) > 0
    )
    try:
        eff_old, eff_new = eff(series[:half]), eff(series[half:])
    except statistics.StatisticsError:
        return OptimizePlanMsg(success=False, reason="no speed samples")
    if eff_new < 0.9 * eff_old:
        return OptimizePlanMsg(success=False, reason="scaling stopped paying")

    if ps_util > 0:
        target = int(cur_workers * threshold / max(ps_util, 1e-6))
        target = max(cur_workers + 1, min(target, cur_workers * 2))
    else:
        target = cur_workers + int(req.config.get("node_unit", 1))
    max_workers = int(req.config.get("max_workers", 0))
    if max_workers and target > max_workers:
        target = max_workers
    if target <= cur_workers:
        return OptimizePlanMsg(success=False, reason="at target already")
    return _plan(**{NodeType.WORKER: GroupResourceMsg(count=target)})


@register("optimize_job_hot_ps_resource")
def hot_ps(store, req: OptimizeRequest) -> OptimizePlanMsg:
    """Double the CPU of PSs running >90% of request
    (``optimize_job_hot_ps_resource.go``)."""
    series = _runtime_series(store, req.job_uuid)
    if not series:
        return OptimizePlanMsg(success=False, reason="no samples")
    plan = OptimizePlanMsg()
    for node in series[-1].get("nodes", {}).get(NodeType.PS, []):
        req_cpu = max(node.get("cpu", 0), 0.1)
        if node.get("used_cpu", 0) / req_cpu > 0.9:
            plan.node_resources[node.get("name", "")] = {
                "cpu": req_cpu * 2,
                "memory": node.get("memory", _PS_COLD.memory),
            }
    if not plan.node_resources:
        return OptimizePlanMsg(success=False, reason="no hot ps")
    return plan


def _oom_adjust(store, req: OptimizeRequest, node_type: str) -> OptimizePlanMsg:
    factor = float(req.config.get("oom_factor", 2.0))
    current = float(req.config.get("current_memory", 0))
    if current <= 0:
        # fall back on the peak observed usage of that node type
        series = _runtime_series(store, req.job_uuid)
        peaks = [
            n.get("used_memory", 0)
            for s in series
            for n in s.get("nodes", {}).get(node_type, [])
        ]
        current = max(peaks) if peaks else _WORKER_COLD.memory
    return _plan(**{
        node_type: GroupResourceMsg(memory=int(current * factor)),
    })


@register("optimize_job_ps_oom_resource")
def ps_oom(store, req: OptimizeRequest) -> OptimizePlanMsg:
    return _oom_adjust(store, req, NodeType.PS)


@register("optimize_job_worker_create_oom_resource")
def worker_oom(store, req: OptimizeRequest) -> OptimizePlanMsg:
    return _oom_adjust(store, req, NodeType.WORKER)
