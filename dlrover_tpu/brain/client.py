"""Brain client + the master-side optimizer/reporter built on it.

Role parity: ``dlrover/python/brain/client.py:63`` (``BrainClient``,
``GlobalBrainClient:280``), ``dlrover/python/master/resource/
brain_optimizer.py`` (``BrainResoureOptimizer``) and the Brain stats
reporter (``dlrover/python/master/stats/reporter.py:55-235``).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from dlrover_tpu.brain.messages import (
    BrainJobMetrics,
    GroupResourceMsg,
    JobMetricsDump,
    JobMetricsQuery,
    MetricType,
    OptimizePlanMsg,
    OptimizeRequest,
)
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.master.resource.local_optimizer import ResourceOptimizer
from dlrover_tpu.master.resource.plan import ResourcePlan
from dlrover_tpu.master.stats.reporter import StatsReporter
from dlrover_tpu.master.stats.training_metrics import (
    DatasetMetric,
    ModelMetric,
    RuntimeMetric,
)
from dlrover_tpu.rpc.client import RpcChannel

logger = get_logger("brain.client")

BRAIN_ADDR_ENV = "DLROVER_BRAIN_ADDR"


class BrainClient:
    def __init__(self, addr: str, timeout: float = 10.0):
        self._channel = RpcChannel(addr, timeout=timeout)

    def persist_metrics(self, metrics: BrainJobMetrics) -> bool:
        return self._channel.report(metrics).success

    def optimize(self, request: OptimizeRequest) -> OptimizePlanMsg:
        return self._channel.get(request)

    def get_job_metrics(
        self, job_uuid: str, metric_type: str = ""
    ) -> List[BrainJobMetrics]:
        dump: JobMetricsDump = self._channel.get(
            JobMetricsQuery(job_uuid=job_uuid, metric_type=metric_type)
        )
        return dump.metrics

    def close(self):
        self._channel.close()


_GLOBAL_CLIENT: Optional[BrainClient] = None


def global_brain_client() -> BrainClient:
    """Singleton from ``DLROVER_BRAIN_ADDR`` (reference
    ``GlobalBrainClient``)."""
    global _GLOBAL_CLIENT
    if _GLOBAL_CLIENT is None:
        addr = os.environ.get(BRAIN_ADDR_ENV, "")
        if not addr:
            raise RuntimeError(f"{BRAIN_ADDR_ENV} is not set")
        _GLOBAL_CLIENT = BrainClient(addr)
    return _GLOBAL_CLIENT


def _plan_from_msg(msg: OptimizePlanMsg) -> Optional[ResourcePlan]:
    if not msg.success:
        return None
    plan = ResourcePlan()
    for node_type, group in msg.group_resources.items():
        g: GroupResourceMsg = group
        plan.node_group_resources[node_type] = NodeGroupResource(
            count=g.count,
            node_resource=NodeResource(cpu=g.cpu, memory=g.memory),
        )
    for name, res in msg.node_resources.items():
        plan.node_resources[name] = NodeResource(
            cpu=res.get("cpu", 0), memory=int(res.get("memory", 0))
        )
    return plan


class BrainResourceOptimizer(ResourceOptimizer):
    """optimize_mode="cluster": plans come from the brain service."""

    def __init__(self, job_name: str, client: Optional[BrainClient] = None):
        self._job_name = job_name
        self._job_uuid = ""
        self._client = client or global_brain_client()

    def update_job_uuid(self, job_uuid: str):
        self._job_uuid = job_uuid

    def generate_opt_plan(self, stage: str = "") -> Optional[ResourcePlan]:
        try:
            msg = self._client.optimize(OptimizeRequest(
                job_uuid=self._job_uuid, job_name=self._job_name,
                stage=stage,
            ))
        except Exception as e:  # noqa: BLE001 — brain outage ≠ job failure
            logger.warning("brain optimize failed: %s", e)
            return None
        return _plan_from_msg(msg)

    def generate_oom_recovery_plan(
        self, node_name: str, current: NodeResource,
        node_type: str = NodeType.WORKER,
    ) -> NodeResource:
        stage = "ps_oom" if node_type == NodeType.PS else "worker_oom"
        try:
            msg = self._client.optimize(OptimizeRequest(
                job_uuid=self._job_uuid, job_name=self._job_name,
                stage=stage,
                config={"current_memory": current.memory},
            ))
        except Exception:  # noqa: BLE001
            msg = OptimizePlanMsg(success=False)
        if msg.success and node_type in msg.group_resources:
            memory = msg.group_resources[node_type].memory
            return NodeResource(cpu=current.cpu, memory=memory)
        return NodeResource(cpu=current.cpu, memory=current.memory * 2)


class BrainStatsReporter(StatsReporter):
    """Forwards the master's metric stream to the brain datastore, giving
    future jobs a history to learn initial plans from."""

    def __init__(self, job_uuid: str, job_name: str,
                 client: Optional[BrainClient] = None):
        self._job_uuid = job_uuid
        self._job_name = job_name
        self._client = client or global_brain_client()

    def _send(self, metric_type: str, payload: dict):
        try:
            self._client.persist_metrics(BrainJobMetrics(
                job_uuid=self._job_uuid, job_name=self._job_name,
                metric_type=metric_type, payload=payload,
                timestamp=time.time(),
            ))
        except Exception as e:  # noqa: BLE001
            logger.warning("brain metric report failed: %s", e)

    def report_dataset_metric(self, metric: DatasetMetric):
        self._send(MetricType.TRAINING_HYPER_PARAMS, {
            "dataset": metric.name, "size": metric.size,
        })

    def report_model_metric(self, metric: ModelMetric):
        self._send(MetricType.MODEL_FEATURE, {
            "param_count": metric.param_count,
            "flops_per_step": metric.flops_per_step,
        })

    def report_runtime_stats(self, metric: RuntimeMetric):
        workers = len(metric.running_nodes.get(NodeType.WORKER, []))
        self._send(MetricType.RUNTIME_INFO, {
            "speed": metric.speed,
            "workers": workers,
            "nodes": metric.running_nodes,
        })

    def report_job_meta(self, **payload):
        self._send(MetricType.JOB_META, payload)

    def report_job_exit(self, reason: str, **payload):
        self._send(MetricType.JOB_EXIT_REASON,
                   {"reason": reason, **payload})
