"""Brain configuration with hot reload.

Role parity: ``dlrover/go/brain/pkg/config/manager.go:180`` — the Go
brain watches a k8s ConfigMap and re-reads algorithm selection at
runtime. Here the source is a JSON file re-checked by mtime on every
read, which a ConfigMap volume mount provides for free on k8s.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

from dlrover_tpu.common.constants import JobStage
from dlrover_tpu.common.log import get_logger

logger = get_logger("brain.config")

DEFAULT_STAGE_ALGORITHMS = {
    JobStage.CREATE: "optimize_job_ps_create_resource",
    JobStage.WORKER_INITIAL: "optimize_job_ps_init_adjust_resource",
    JobStage.RUNNING: "optimize_job_worker_resource",
    "hot_ps": "optimize_job_hot_ps_resource",
    "ps_oom": "optimize_job_ps_oom_resource",
    "worker_oom": "optimize_job_worker_create_oom_resource",
}


class BrainConfig:
    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._mtime = 0.0
        self._lock = threading.Lock()
        self._data: Dict = {}
        self._reload_if_changed(force=True)

    def _reload_if_changed(self, force: bool = False):
        if not self._path:
            return
        try:
            mtime = os.path.getmtime(self._path)
        except OSError:
            return
        if not force and mtime == self._mtime:
            return
        try:
            with open(self._path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("brain config reload failed: %s", e)
            return
        with self._lock:
            self._data = data
            self._mtime = mtime
        logger.info("brain config (re)loaded from %s", self._path)

    def algorithm_for(self, stage: str) -> str:
        self._reload_if_changed()
        with self._lock:
            table = {
                **DEFAULT_STAGE_ALGORITHMS,
                **self._data.get("stage_algorithms", {}),
            }
        return table.get(stage, "")

    def algorithm_config(self, algorithm: str) -> Dict:
        self._reload_if_changed()
        with self._lock:
            return dict(self._data.get("algorithm_configs", {}).get(
                algorithm, {}
            ))
