"""Brain service: cluster-level resource optimizer.

Role parity: ``dlrover/go/brain/pkg/server/server.go:39-176``
(``BrainServer`` gRPC: persist_metrics / optimize / get_job_metrics).
Runs over the same codegen-free two-method transport as the master
(``rpc.server``): metric reports arrive via ``report``, optimize and
query via ``get``.
"""

from __future__ import annotations

from typing import Optional

from dlrover_tpu.brain.algorithms import get_algorithm
from dlrover_tpu.brain.config import BrainConfig
from dlrover_tpu.brain.datastore import BaseDatastore, new_datastore
from dlrover_tpu.brain.messages import (
    BrainJobMetrics,
    JobMetricsDump,
    JobMetricsQuery,
    OptimizePlanMsg,
    OptimizeRequest,
)
from dlrover_tpu.common.comm import Response
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.rpc.server import build_server

logger = get_logger("brain.service")


class BrainServicer:
    def __init__(
        self,
        datastore: Optional[BaseDatastore] = None,
        config: Optional[BrainConfig] = None,
    ):
        self._store = datastore or new_datastore("memory")
        self._config = config or BrainConfig()

    @property
    def datastore(self) -> BaseDatastore:
        return self._store

    # -- transport entry points (rpc.server contract) -----------------------

    def report(self, request, context=None) -> Response:
        if isinstance(request, BrainJobMetrics):
            self._store.persist_metrics(request)
            return Response(success=True)
        return Response(success=False, reason=f"unknown {type(request).__name__}")

    def get(self, request, context=None):
        if isinstance(request, OptimizeRequest):
            return self.optimize(request)
        if isinstance(request, JobMetricsQuery):
            return JobMetricsDump(
                job_uuid=request.job_uuid,
                metrics=self._store.get_job_metrics(
                    request.job_uuid, request.metric_type
                ),
            )
        return Response(success=False, reason=f"unknown {type(request).__name__}")

    # -- logic --------------------------------------------------------------

    def optimize(self, req: OptimizeRequest) -> OptimizePlanMsg:
        name = req.algorithm or self._config.algorithm_for(req.stage)
        algo = get_algorithm(name)
        if algo is None:
            return OptimizePlanMsg(
                success=False, reason=f"no algorithm for stage {req.stage!r}"
            )
        config = {**self._config.algorithm_config(name), **req.config}
        merged = OptimizeRequest(
            job_uuid=req.job_uuid, job_name=req.job_name,
            stage=req.stage, algorithm=name, config=config,
        )
        try:
            plan = algo(self._store, merged)
        except Exception as e:  # noqa: BLE001 — servable errors, not crashes
            logger.exception("algorithm %s failed", name)
            return OptimizePlanMsg(success=False, reason=str(e)[:200])
        logger.info(
            "optimize job=%s stage=%s algo=%s -> success=%s",
            req.job_name, req.stage, name, plan.success,
        )
        return plan


class BrainService:
    """gRPC-served brain (`python -m dlrover_tpu.brain.main`)."""

    def __init__(
        self,
        port: int = 0,
        datastore_spec: str = "memory",
        config_path: Optional[str] = None,
    ):
        self.servicer = BrainServicer(
            datastore=new_datastore(datastore_spec),
            config=BrainConfig(config_path),
        )
        self._server, self.port = build_server(self.servicer, port=port)

    def start(self):
        self._server.start()
        logger.info("brain service listening on :%d", self.port)

    def stop(self, grace: float = 1.0):
        self._server.stop(grace)
