"""``python -m dlrover_tpu.brain.main`` — run the brain service.

Role parity: the Go brain's server binary
(``dlrover/go/brain/cmd/brain/main.go``); ``--watch-cluster`` folds in
the ``k8smonitor`` role (``go/brain/pkg/platform/k8s/watcher``): a
cluster watcher feeding the same datastore, so jobs leave history even
without self-reporting.
"""

from __future__ import annotations

import argparse
import signal
import threading

from dlrover_tpu.brain.service import BrainService


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("dlrover-tpu brain")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument(
        "--datastore", default="memory",
        help='"memory" or "sqlite:///path/to.db"',
    )
    parser.add_argument(
        "--config", default="",
        help="JSON config file (hot-reloaded; ConfigMap-mountable)",
    )
    parser.add_argument(
        "--watch-cluster", action="store_true",
        help="run the k8s cluster watcher (the k8smonitor role) "
             "against the in-process datastore",
    )
    parser.add_argument(
        "--namespace", default="default",
        help="namespace for --watch-cluster",
    )
    parser.add_argument("--watch-interval", type=float, default=30.0)
    args = parser.parse_args(argv)

    service = BrainService(
        port=args.port,
        datastore_spec=args.datastore,
        config_path=args.config or None,
    )
    service.start()
    watcher = None
    if args.watch_cluster:
        from dlrover_tpu.brain.watcher import (
            ClusterWatcher,
            K8sClusterSource,
        )
        from dlrover_tpu.scheduler.kubernetes import K8sClient

        watcher = ClusterWatcher(
            sink=service.servicer.datastore,
            source=K8sClusterSource(
                K8sClient.singleton_instance(args.namespace)
            ),
            interval=args.watch_interval,
        )
        watcher.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if watcher is not None:
        watcher.stop()
    service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
