"""``python -m dlrover_tpu.brain.main`` — run the brain service.

Role parity: the Go brain's server binary
(``dlrover/go/brain/cmd/brain/main.go``).
"""

from __future__ import annotations

import argparse
import signal
import threading

from dlrover_tpu.brain.service import BrainService


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("dlrover-tpu brain")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument(
        "--datastore", default="memory",
        help='"memory" or "sqlite:///path/to.db"',
    )
    parser.add_argument(
        "--config", default="",
        help="JSON config file (hot-reloaded; ConfigMap-mountable)",
    )
    args = parser.parse_args(argv)

    service = BrainService(
        port=args.port,
        datastore_spec=args.datastore,
        config_path=args.config or None,
    )
    service.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
